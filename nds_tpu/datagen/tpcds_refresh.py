"""Refresh (update-set) data generator for the NDS data-maintenance phase.

The reference gets refresh data from ``dsdgen --update N``
(`nds/nds_gen_data.py:183-244` with ``--update``, moved delete tables
`:119-127`); this is the builtin hermetic counterpart: the 10 s_*
staging tables plus the two delete-window tables, sized by scale factor
and deterministic on (SEED, update, table).

Business-ID consistency contract (what the LF_* refresh functions join
on, `nds/data_maintenance/LF_SS.sql`, `LF_CS.sql`, `LF_I.sql`):
- *_item_id / *_store_id / *_call_center_id / *_web_site_id /
  *_web_page_id reference the CURRENT SCD record of the base dimension
  (ids repeat across the 2-row history; the odd surrogate key is the
  open record with NULL rec_end_date — see `tpcds._gen_item`);
- *_customer_id / *_warehouse_id / *_promotion_id / ship-mode / reason
  ids cover the full base domain (no SCD);
- catalog lineitems address real (cp_catalog_number,
  cp_catalog_page_number) pairs;
- order/purchase dates land in a per-update window AFTER the base sales
  window (inserts extend history), while the delete tables' [date1,
  date2] windows land INSIDE it (deletes remove base rows) — dsdgen's
  refresh semantics.
- returns staging rows reference EXISTING ticket/order numbers so
  inserted returns join back to sales.

Times are integer seconds-since-midnight (join t_time directly) and
dates are engine DATE epoch days: the builtin generator owns the raw
format, so the reference's ``cast(char AS date)`` / substr-time hops are
unnecessary (see `nds_tpu/nds/schema.py:get_maintenance_schemas`).
"""

from __future__ import annotations

import numpy as np

from nds_tpu.datagen.tpcds import (
    SALES_DATE_HI, SALES_DATE_LO, SEED, _choice, _h, _ids, _uniform,
    sk_to_epoch,
)
from nds_tpu.nds.schema import table_rows

# insert window: one week per update, after the base sales window
_INSERT_BASE = sk_to_epoch(SALES_DATE_HI)
# delete window: 30 days, walking through the base window per update
_BASE_LO = sk_to_epoch(SALES_DATE_LO)
_BASE_DAYS = SALES_DATE_HI - SALES_DATE_LO


def _n_orders(channel_rows: int) -> int:
    """Refresh set size: ~0.1% of the channel's base tickets (>= 8)."""
    return max(channel_rows // 10 // 1000, 8)


def _current_id(h, table: str, sf: float) -> np.ndarray:
    """Business id of a CURRENT (open SCD record) dimension row."""
    n = table_rows(table, sf)
    return _ids("AAAAAAAA", _uniform(h, 1, max((n + 1) // 2, 1)))


def _full_id(h, table: str, sf: float) -> np.ndarray:
    n = table_rows(table, sf)
    return _ids("AAAAAAAA", _uniform(h, 1, max(n, 1)))


def _insert_dates(h, update: int) -> np.ndarray:
    lo = _INSERT_BASE + (update - 1) * 7 + 1
    return _uniform(h, lo, lo + 6)


def _money(h, lo=99, hi=9999) -> np.ndarray:
    return _uniform(h, lo, hi)  # cents (scaled decimal(7,2))


def _orders_lineitems(seed_tag: str, sf: float, update: int,
                      channel_rows: int):
    """Shared order/lineitem shape: order i has (i % 3) + 1 lines.
    Returns (order_ids, per-order hash fn, line order-idx, line#,
    per-line hash fn)."""
    n = _n_orders(channel_rows)
    oidx = np.arange(n, dtype=np.int64)
    # ids disjoint from base ticket/order numbers (which are ~rows/10):
    # park refresh ids in a high block keyed by update number
    base = 1_000_000_000 + (update - 1) * 10_000_000
    order_ids = base + oidx + 1
    lines = (oidx % 3) + 1
    lidx_order = np.repeat(oidx, lines)
    line_no = (np.arange(len(lidx_order), dtype=np.int64)
               - np.repeat(np.cumsum(lines) - lines, lines)) + 1
    oh = lambda k: _h(SEED, seed_tag + f"#u{update}", k, oidx)
    lh = lambda k: _h(SEED, seed_tag + f"#ul{update}", k,
                      np.arange(len(lidx_order), dtype=np.int64))
    return order_ids, oh, lidx_order, line_no, lh


def _gen_purchase_pair(sf: float, update: int):
    rows = table_rows("store_sales", sf)
    ids, oh, lo_idx, line_no, lh = _orders_lineitems(
        "s_purchase", sf, update, rows)
    purchase = {
        "purc_purchase_id": ids.astype(np.int32),
        "purc_store_id": _current_id(oh(1), "store", sf),
        "purc_customer_id": _full_id(oh(2), "customer", sf),
        "purc_purchase_date": _insert_dates(oh(3), update
                                            ).astype(np.int32),
        "purc_purchase_time": _uniform(oh(4), 0, 86399).astype(np.int32),
        "purc_register_id": _uniform(oh(5), 1, 40).astype(np.int32),
        "purc_clerk_id": _uniform(oh(6), 1, 200).astype(np.int32),
        "purc_comment": _choice(oh(7), ["in store purchase",
                                        "holiday purchase",
                                        "regular purchase"]),
    }
    qty = _uniform(lh(3), 1, 100)
    sale = _money(lh(4))
    lineitem = {
        "plin_purchase_id": ids[lo_idx].astype(np.int32),
        "plin_line_number": line_no.astype(np.int32),
        "plin_item_id": _current_id(lh(1), "item", sf),
        "plin_promotion_id": _full_id(lh(2), "promotion", sf),
        "plin_quantity": qty.astype(np.int32),
        "plin_sale_price": sale.astype(np.int64),
        "plin_coupon_amt": np.where(
            lh(5) % np.uint64(100) < np.uint64(15),
            sale * qty // 10, 0).astype(np.int64),
        "plin_comment": _choice(lh(6), ["line comment", "gift wrap",
                                        "no comment"]),
    }
    return purchase, lineitem


def _gen_catalog_pair(sf: float, update: int):
    rows = table_rows("catalog_sales", sf)
    ids, oh, lo_idx, line_no, lh = _orders_lineitems(
        "s_catalog_order", sf, update, rows)
    order = {
        "cord_order_id": ids.astype(np.int32),
        "cord_bill_customer_id": _full_id(oh(1), "customer", sf),
        "cord_ship_customer_id": _full_id(oh(2), "customer", sf),
        "cord_order_date": _insert_dates(oh(3), update).astype(np.int32),
        "cord_order_time": _uniform(oh(4), 0, 86399).astype(np.int32),
        "cord_ship_mode_id": _full_id(oh(5), "ship_mode", sf),
        "cord_call_center_id": _current_id(oh(6), "call_center", sf),
        "cord_order_comments": _choice(oh(7), ["phone order",
                                               "catalog order",
                                               "repeat order"]),
    }
    n_cp = table_rows("catalog_page", sf)
    cp_idx = _uniform(lh(7), 0, max(n_cp - 1, 0))
    qty = _uniform(lh(3), 1, 100)
    sale = _money(lh(4))
    lineitem = {
        "clin_order_id": ids[lo_idx].astype(np.int32),
        "clin_line_number": line_no.astype(np.int32),
        "clin_item_id": _current_id(lh(1), "item", sf),
        "clin_promotion_id": _full_id(lh(2), "promotion", sf),
        "clin_quantity": qty.astype(np.int32),
        "clin_sales_price": sale.astype(np.int64),
        "clin_coupon_amt": np.where(
            lh(5) % np.uint64(100) < np.uint64(15),
            sale * qty // 10, 0).astype(np.int64),
        "clin_warehouse_id": _full_id(lh(6), "warehouse", sf),
        "clin_ship_date": (_insert_dates(lh(8), update) + 3
                           ).astype(np.int32),
        "clin_catalog_number": (cp_idx // 108 + 1).astype(np.int32),
        "clin_catalog_page_number": (cp_idx % 108 + 1).astype(np.int32),
        "clin_ship_cost": _money(lh(9), 0, 2000).astype(np.int64),
    }
    return order, lineitem


def _gen_web_pair(sf: float, update: int):
    rows = table_rows("web_sales", sf)
    ids, oh, lo_idx, line_no, lh = _orders_lineitems(
        "s_web_order", sf, update, rows)
    order = {
        "word_order_id": ids.astype(np.int32),
        "word_bill_customer_id": _full_id(oh(1), "customer", sf),
        "word_ship_customer_id": _full_id(oh(2), "customer", sf),
        "word_order_date": _insert_dates(oh(3), update).astype(np.int32),
        "word_order_time": _uniform(oh(4), 0, 86399).astype(np.int32),
        "word_ship_mode_id": _full_id(oh(5), "ship_mode", sf),
        "word_web_site_id": _current_id(oh(6), "web_site", sf),
        "word_order_comments": _choice(oh(7), ["web order",
                                               "mobile order",
                                               "repeat order"]),
    }
    qty = _uniform(lh(3), 1, 100)
    sale = _money(lh(4))
    lineitem = {
        "wlin_order_id": ids[lo_idx].astype(np.int32),
        "wlin_line_number": line_no.astype(np.int32),
        "wlin_item_id": _current_id(lh(1), "item", sf),
        "wlin_promotion_id": _full_id(lh(2), "promotion", sf),
        "wlin_quantity": qty.astype(np.int32),
        "wlin_sales_price": sale.astype(np.int64),
        "wlin_coupon_amt": np.where(
            lh(5) % np.uint64(100) < np.uint64(15),
            sale * qty // 10, 0).astype(np.int64),
        "wlin_warehouse_id": _full_id(lh(6), "warehouse", sf),
        "wlin_ship_date": (_insert_dates(lh(8), update) + 2
                           ).astype(np.int32),
        "wlin_ship_cost": _money(lh(9), 0, 2000).astype(np.int64),
        "wlin_web_page_id": _current_id(lh(7), "web_page", sf),
    }
    return order, lineitem


def _return_money(h):
    amt = _money(h(10))
    tax = amt * _uniform(h(11), 0, 9) // 100
    fee = _money(h(12), 0, 100)
    ship = _money(h(13), 0, 500)
    refunded = amt * _uniform(h(14), 0, 100) // 100
    reversed_c = (amt - refunded) * _uniform(h(15), 0, 100) // 100
    credit = amt - refunded - reversed_c
    return amt, tax, fee, ship, refunded, reversed_c, credit


def _gen_s_store_returns(sf: float, update: int):
    n = max(_n_orders(table_rows("store_sales", sf)) // 2, 4)
    idx = np.arange(n, dtype=np.int64)
    h = lambda k: _h(SEED, f"s_store_returns#u{update}", k, idx)
    n_tickets = max(table_rows("store_sales", sf) // 10, 1)
    ticket = _uniform(h(1), 1, n_tickets)
    amt, tax, fee, ship, refunded, reversed_c, credit = _return_money(h)
    return {
        "sret_store_id": _current_id(h(2), "store", sf),
        "sret_purchase_id": _ids("", ticket, 16),
        "sret_line_number": _uniform(h(3), 1, 16).astype(np.int32),
        "sret_item_id": _current_id(h(4), "item", sf),
        "sret_customer_id": _full_id(h(5), "customer", sf),
        "sret_return_date": (_insert_dates(h(6), update) + 1
                             ).astype(np.int32),
        "sret_return_time": _uniform(h(7), 0, 86399).astype(np.int32),
        "sret_ticket_number": ticket.astype(np.int64),
        "sret_return_qty": _uniform(h(8), 1, 50).astype(np.int32),
        "sret_return_amt": amt.astype(np.int64),
        "sret_return_tax": tax.astype(np.int64),
        "sret_return_fee": fee.astype(np.int64),
        "sret_return_ship_cost": ship.astype(np.int64),
        "sret_refunded_cash": refunded.astype(np.int64),
        "sret_reversed_charge": reversed_c.astype(np.int64),
        "sret_store_credit": credit.astype(np.int64),
        "sret_reason_id": _full_id(h(9), "reason", sf),
    }


def _gen_s_catalog_returns(sf: float, update: int):
    n = max(_n_orders(table_rows("catalog_sales", sf)) // 2, 4)
    idx = np.arange(n, dtype=np.int64)
    h = lambda k: _h(SEED, f"s_catalog_returns#u{update}", k, idx)
    n_orders = max(table_rows("catalog_sales", sf) // 10, 1)
    order = _uniform(h(1), 1, n_orders)
    amt, tax, fee, ship, refunded, reversed_c, credit = _return_money(h)
    n_cp = table_rows("catalog_page", sf)
    return {
        "cret_call_center_id": _current_id(h(2), "call_center", sf),
        "cret_order_id": order.astype(np.int32),
        "cret_line_number": _uniform(h(3), 1, 16).astype(np.int32),
        "cret_item_id": _current_id(h(4), "item", sf),
        "cret_return_customer_id": _full_id(h(5), "customer", sf),
        "cret_refund_customer_id": _full_id(h(16), "customer", sf),
        "cret_return_date": (_insert_dates(h(6), update) + 1
                             ).astype(np.int32),
        "cret_return_time": _uniform(h(7), 0, 86399).astype(np.int32),
        "cret_return_qty": _uniform(h(8), 1, 50).astype(np.int32),
        "cret_return_amt": amt.astype(np.int64),
        "cret_return_tax": tax.astype(np.int64),
        "cret_return_fee": fee.astype(np.int64),
        "cret_return_ship_cost": ship.astype(np.int64),
        "cret_refunded_cash": refunded.astype(np.int64),
        "cret_reversed_charge": reversed_c.astype(np.int64),
        "cret_merchant_credit": credit.astype(np.int64),
        "cret_reason_id": _full_id(h(9), "reason", sf),
        "cret_shipmode_id": _full_id(h(17), "ship_mode", sf),
        "cret_catalog_page_id": _ids(
            "AAAAAAAA", _uniform(h(18), 1, max(n_cp, 1))),
        "cret_warehouse_id": _full_id(h(19), "warehouse", sf),
    }


def _gen_s_web_returns(sf: float, update: int):
    n = max(_n_orders(table_rows("web_sales", sf)) // 2, 4)
    idx = np.arange(n, dtype=np.int64)
    h = lambda k: _h(SEED, f"s_web_returns#u{update}", k, idx)
    n_orders = max(table_rows("web_sales", sf) // 10, 1)
    order = _uniform(h(1), 1, n_orders)
    amt, tax, fee, ship, refunded, reversed_c, credit = _return_money(h)
    return {
        "wret_web_page_id": _current_id(h(2), "web_page", sf),
        "wret_order_id": order.astype(np.int32),
        "wret_line_number": _uniform(h(3), 1, 16).astype(np.int32),
        "wret_item_id": _current_id(h(4), "item", sf),
        "wret_return_customer_id": _full_id(h(5), "customer", sf),
        "wret_refund_customer_id": _full_id(h(16), "customer", sf),
        "wret_return_date": (_insert_dates(h(6), update) + 1
                             ).astype(np.int32),
        "wret_return_time": _uniform(h(7), 0, 86399).astype(np.int32),
        "wret_return_qty": _uniform(h(8), 1, 50).astype(np.int32),
        "wret_return_amt": amt.astype(np.int64),
        "wret_return_tax": tax.astype(np.int64),
        "wret_return_fee": fee.astype(np.int64),
        "wret_return_ship_cost": ship.astype(np.int64),
        "wret_refunded_cash": refunded.astype(np.int64),
        "wret_reversed_charge": reversed_c.astype(np.int64),
        "wret_account_credit": credit.astype(np.int64),
        "wret_reason_id": _full_id(h(9), "reason", sf),
    }


def _gen_s_inventory(sf: float, update: int):
    n_item = table_rows("item", sf)
    n_wh = table_rows("warehouse", sf)
    n = max(min(n_item * n_wh // 4, 4000), 8)
    idx = np.arange(n, dtype=np.int64)
    h = lambda k: _h(SEED, f"s_inventory#u{update}", k, idx)
    # one refresh snapshot date per update week
    date = np.full(n, _INSERT_BASE + (update - 1) * 7 + 4, dtype=np.int64)
    return {
        "invn_warehouse_id": _full_id(h(1), "warehouse", sf),
        "invn_item_id": _current_id(h(2), "item", sf),
        "invn_date": date.astype(np.int32),
        "invn_qty_on_hand": _uniform(h(3), 0, 1000).astype(np.int32),
    }


def _delete_window(update: int, widen: int = 0):
    start = _BASE_LO + ((update * 89) % max(_BASE_DAYS - 30, 1))
    return start, start + 30 + widen


def _gen_delete(sf: float, update: int):
    d1, d2 = _delete_window(update)
    return {"date1": np.array([d1], dtype=np.int32),
            "date2": np.array([d2], dtype=np.int32)}


def _gen_inventory_delete(sf: float, update: int):
    # inventory snapshots are weekly from the START of the base window
    # and only ~rows/(items*warehouses) weeks exist at small SF, so the
    # window walks the early weeks (and is widened past one week) to
    # guarantee it covers generated snapshots at every scale
    d1 = _BASE_LO + (update - 1) * 21
    return {"date1": np.array([d1], dtype=np.int32),
            "date2": np.array([d1 + 37], dtype=np.int32)}


def gen_refresh_table(table: str, sf: float, update: int = 1
                      ) -> dict[str, np.ndarray]:
    """Refresh arrays for one maintenance table (update >= 1)."""
    if update < 1:
        raise ValueError(f"update must be >= 1, got {update}")
    pairs = {
        "s_purchase": 0, "s_purchase_lineitem": 1,
        "s_catalog_order": 0, "s_catalog_order_lineitem": 1,
        "s_web_order": 0, "s_web_order_lineitem": 1,
    }
    if table in ("s_purchase", "s_purchase_lineitem"):
        return _gen_purchase_pair(sf, update)[pairs[table]]
    if table in ("s_catalog_order", "s_catalog_order_lineitem"):
        return _gen_catalog_pair(sf, update)[pairs[table]]
    if table in ("s_web_order", "s_web_order_lineitem"):
        return _gen_web_pair(sf, update)[pairs[table]]
    fns = {
        "s_store_returns": _gen_s_store_returns,
        "s_catalog_returns": _gen_s_catalog_returns,
        "s_web_returns": _gen_s_web_returns,
        "s_inventory": _gen_s_inventory,
        "delete": _gen_delete,
        "inventory_delete": _gen_inventory_delete,
    }
    fn = fns.get(table)
    if fn is None:
        raise ValueError(f"unknown maintenance table {table!r}")
    return fn(sf, update)
