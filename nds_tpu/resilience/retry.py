"""Failure classification + retry policy with backoff and deadlines.

Accelerator runtimes fail in modes classic SQL engines never see
(PAPERS.md, Query Processing on Tensor Computation Runtimes): HBM
exhaustion and compile-time resource errors are TRANSIENT — a retry
after freeing buffers, shrinking chunks, or doubling exchange slack
usually succeeds — while parse/plan/verify errors are DETERMINISTIC
and retrying them just triples the time to the same stack trace. This
module is the single place that distinction lives:

- ``classify(exc)`` -> TRANSIENT | DETERMINISTIC. Transient: injected
  faults (``resilience.faults``), RESOURCE_EXHAUSTED / out-of-memory
  (jaxlib's XlaRuntimeError vocabulary), exchange-capacity overflow.
  Everything else — parse/plan/verify errors included — is
  deterministic and never retried.
- ``RetryPolicy`` — attempt cap, exponential backoff with seeded
  deterministic jitter, and a per-query wall-clock deadline. Owned by
  the unified execution pipeline (``engine/scheduler.py``), which runs
  every query's retry + degradation-ladder walk; the executors'
  slack-doubling loops (``parallel/dist_exec.py``,
  ``engine/chunked_exec.py``) borrow no-sleep policies from
  ``scheduler.adaptive_policy`` and share ``attempts()``.

Config keys (README "Resilience"): ``engine.retry.max_attempts``,
``engine.retry.base_delay_s``, ``engine.retry.max_delay_s``,
``engine.retry.jitter``, ``engine.query_deadline_s``. Metrics:
``query_retries_total``, ``query_deadline_exceeded_total``.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable

from nds_tpu.resilience import faults as faults_mod

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"


class QueryDeadlineExceeded(RuntimeError):
    """Raised by ``check_deadline()`` when the active deadline scope has
    expired MID-attempt — long-running loop bodies (the chunked
    executor's per-chunk loops) call it between iterations so a
    deadlined query stops at the next chunk boundary instead of
    finishing a doomed attempt. Deterministic: the wall clock cannot be
    retried back."""


# active per-call deadline, published by RetryPolicy.call so code deep
# inside an attempt can honor it; thread-local because concurrent
# in-process streams carry independent deadlines
_deadline = threading.local()


@contextmanager
def deadline_scope(deadline_s: float | None,
                   clock: Callable[[], float] = time.monotonic,
                   start: float | None = None):
    """Publish an absolute deadline for the block (no-op when
    ``deadline_s`` is None); nests — the innermost scope wins."""
    if deadline_s is None:
        yield
        return
    prev = getattr(_deadline, "v", None)
    _deadline.v = ((start if start is not None else clock())
                   + deadline_s, clock)
    try:
        yield
    finally:
        _deadline.v = prev


def check_deadline() -> None:
    """Raise QueryDeadlineExceeded when the active scope's deadline has
    passed; no-op outside any scope. Cheap enough for per-chunk
    granularity (one thread-local read + one clock read)."""
    v = getattr(_deadline, "v", None)
    if v is not None and v[1]() > v[0]:
        raise QueryDeadlineExceeded(
            "query deadline exceeded mid-attempt "
            "(engine.query_deadline_s)")

# message fragments that mark a transient accelerator/runtime failure
# (jaxlib surfaces device OOM as XlaRuntimeError("RESOURCE_EXHAUSTED:
# ..."); the exchange retry loop raises on persisted overflow)
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
    "Out of memory",
    "exchange overflow",
)


def is_oom(exc: BaseException) -> bool:
    """Device-memory exhaustion specifically (the chunked executor
    halves its chunk size on these before giving up)."""
    if isinstance(exc, faults_mod.InjectedOOM):
        return True
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "ut of memory" in msg


def classify(exc: BaseException) -> str:
    """TRANSIENT (worth retrying) or DETERMINISTIC (never retry).

    Unknown exception types default to DETERMINISTIC: retrying a
    planner bug burns the attempt budget to reach the same stack
    trace, while a mis-classified transient costs one lost retry —
    the conservative direction."""
    if isinstance(exc, faults_mod.InjectedDeterministicFault):
        return DETERMINISTIC
    if isinstance(exc, faults_mod.InjectedTransientFault):
        return TRANSIENT
    if isinstance(exc, QueryDeadlineExceeded):
        return DETERMINISTIC
    from nds_tpu.io.integrity import CorruptArtifact
    if isinstance(exc, CorruptArtifact):
        # re-reading corrupt bytes yields the same corrupt bytes:
        # explicitly deterministic even if a message ever carried a
        # transient marker
        return DETERMINISTIC
    msg = str(exc)
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return TRANSIENT
    return DETERMINISTIC


@dataclass
class RetryStats:
    """Per-call accounting the BenchReport summary picks up
    (``retries`` / ``gave_up_reason`` / ``deadline_exceeded``)."""
    attempts: int = 0
    retries: int = 0
    gave_up_reason: str | None = None
    deadline_exceeded: bool = False
    backoff_s: float = 0.0
    errors: list = field(default_factory=list)


class RetryPolicy:
    """Exponential backoff with seeded jitter, attempt cap, and an
    optional per-call wall-clock deadline.

    Delay for retry *i* (0-based) is
    ``min(base_delay_s * 2**i, max_delay_s)`` plus a deterministic
    jitter fraction drawn from ``seed`` — two runs with the same seed
    back off identically (chaos runs must replay exactly)."""

    def __init__(self, max_attempts: int = 3,
                 base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0,
                 jitter: float = 0.25,
                 deadline_s: float | None = None,
                 seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.seed = seed
        self._sleep = sleep
        self._clock = clock

    @classmethod
    def from_config(cls, config, **kw) -> "RetryPolicy":
        """Build from an EngineConfig (``engine.retry.*`` +
        ``engine.query_deadline_s``)."""
        def _f(key, default):
            v = config.get(key)
            return default if v is None else float(v)
        deadline = _f("engine.query_deadline_s", 0.0)
        return cls(
            max_attempts=config.get_int("engine.retry.max_attempts", 3),
            base_delay_s=_f("engine.retry.base_delay_s", 0.05),
            max_delay_s=_f("engine.retry.max_delay_s", 2.0),
            jitter=_f("engine.retry.jitter", 0.25),
            deadline_s=deadline if deadline > 0 else None,
            seed=config.get_int("engine.retry.seed", 0), **kw)

    def with_attempts(self, max_attempts: int) -> "RetryPolicy":
        """Derived policy with a different attempt budget and every
        other field (sleep/clock injection included) preserved — for
        callers that already spent attempts outside the policy (the
        throughput stream rerun)."""
        return RetryPolicy(
            max_attempts=max_attempts,
            base_delay_s=self.base_delay_s,
            max_delay_s=self.max_delay_s, jitter=self.jitter,
            deadline_s=self.deadline_s, seed=self.seed,
            sleep=self._sleep, clock=self._clock)

    def delay_for(self, retry_index: int) -> float:
        base = min(self.base_delay_s * (2 ** retry_index),
                   self.max_delay_s)
        if base <= 0 or self.jitter <= 0:
            return max(base, 0.0)
        key = f"{self.seed}:{retry_index}"
        return base * (1.0 + self.jitter
                       * random.Random(key.encode()).random())

    def attempts(self):
        """Attempt-index iterator for executor-internal retry loops
        (the exchange slack-doubling / chunk-shrinking shape): yields
        0..max_attempts-1, sleeping the backoff BETWEEN attempts. The
        loop body decides what changes per attempt and raises when the
        budget is spent."""
        for i in range(self.max_attempts):
            if i:
                d = self.delay_for(i - 1)
                if d > 0:
                    self._sleep(d)
            yield i

    def call(self, fn: Callable, *args,
             stats: RetryStats | None = None,
             classify_fn: Callable[[BaseException], str] = classify,
             on_retry: Callable[[BaseException, int], None] | None = None):
        """Run ``fn(*args)`` under the policy; returns its result.

        Transient failures retry with backoff until the attempt cap or
        the deadline; deterministic failures re-raise immediately. The
        final exception always propagates — callers that must swallow
        it (the power loop's report bracket) already do. ``stats``
        (optional, caller-owned) receives the accounting either way;
        a success that still overran the deadline is returned but
        flagged ``deadline_exceeded`` (and counted), since its wall
        clock already damaged the run it was deadlined for.

        The deadline is also enforced INSIDE an attempt: the call runs
        under ``deadline_scope``, so loop bodies that poll
        ``check_deadline()`` (the chunked executor, between chunks)
        abort mid-attempt with QueryDeadlineExceeded; and a FINAL
        attempt that fails after overrunning the deadline still records
        ``deadline_exceeded`` alongside its ``gave_up_reason`` — the
        overrun happened whether or not the attempt also raised."""
        stats = stats if stats is not None else RetryStats()
        start = self._clock()

        def _overrun() -> bool:
            return (self.deadline_s is not None
                    and self._clock() - start > self.deadline_s)

        def _flag_deadline() -> None:
            from nds_tpu.obs import metrics as obs_metrics
            if not stats.deadline_exceeded:
                stats.deadline_exceeded = True
                obs_metrics.counter(
                    "query_deadline_exceeded_total").inc()

        with deadline_scope(self.deadline_s, self._clock, start=start):
            while True:
                stats.attempts += 1
                try:
                    result = fn(*args)
                except QueryDeadlineExceeded as exc:
                    # an in-attempt deadline abort IS the deadline
                    # giving up, not a deterministic engine bug
                    stats.errors.append(
                        f"{type(exc).__name__}: {exc}")
                    stats.gave_up_reason = "deadline"
                    _flag_deadline()
                    raise
                except Exception as exc:  # noqa: BLE001 - classified below
                    stats.errors.append(f"{type(exc).__name__}: {exc}")
                    if classify_fn(exc) != TRANSIENT:
                        stats.gave_up_reason = DETERMINISTIC
                        if _overrun():
                            _flag_deadline()
                        raise
                    if stats.attempts >= self.max_attempts:
                        stats.gave_up_reason = (
                            f"attempts_exhausted({stats.attempts})")
                        if _overrun():
                            _flag_deadline()
                        raise
                    d = self.delay_for(stats.retries)
                    if (self.deadline_s is not None
                            and self._clock() - start + d
                            > self.deadline_s):
                        stats.gave_up_reason = "deadline"
                        _flag_deadline()
                        raise
                    from nds_tpu.obs import metrics as obs_metrics
                    stats.retries += 1
                    stats.backoff_s += d
                    obs_metrics.counter("query_retries_total").inc()
                    if on_retry is not None:
                        on_retry(exc, stats.retries)
                    if d > 0:
                        self._sleep(d)
                    continue
                if _overrun():
                    _flag_deadline()
                return result
