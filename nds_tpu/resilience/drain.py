"""Graceful preemption drain: SIGTERM/SIGINT as a scheduling event.

Multi-hour accelerator occupancy makes preemption a certainty, not a
risk ("Large Scale Distributed Linear Algebra With TPUs" — PAPERS.md),
and a preempted run that dies mid-flight throws away everything the
query-granular journal (resilience/journal.py) exists to preserve.
This module turns the kill signal into a drain:

- **First SIGTERM/SIGINT** — a chaining handler (it captures the
  previous handler with ``signal.getsignal`` and restores it on
  uninstall; ndslint NDS114 flags the discard pattern) performs the
  bounded flight-dump + trace flush the PR-9 SIGTERM handler used to
  own (obs/fleet.signal_flush — same lock-safe, timeout-bounded path),
  marks the drain REQUESTED, and arms a deadline thread. The in-flight
  query keeps running: the power loop checks :func:`check_boundary`
  between statements and exits with :data:`EXIT_RESUMABLE` (75, BSD
  EX_TEMPFAIL) once the query finished — journal, summaries, snapshot
  and trace all flush through the normal teardown path.

- **Past the deadline** (``engine.drain_s`` / ``NDS_TPU_DRAIN_S``,
  default 30 s) — the in-flight query is abandoned: registered flush
  hooks run (the power loop journals the query as explicitly
  not-done via ``QueryJournal.mark_aborted`` and writes a final
  metrics snapshot), the flight recorder dumps once more, and the
  process hard-exits 75. The journal already holds every COMPLETED
  query (appended per statement, atomically), so the abandonment
  loses exactly the one in-flight statement.

- **Repeat signal** — the operator (or a supervisor escalating) wants
  out now: flush hooks run immediately and the process exits 75
  without waiting out the deadline.

Exit 75 is the RESUMABLE contract: ``StreamSupervisor``
(resilience/supervise.py) relaunches a 75-exit stream without charging
its restart budget, and ``nds/bench.py`` re-runs a 75-exit power phase
with ``--resume`` instead of failing the bench.
"""

from __future__ import annotations

import os
import signal
import threading

# BSD EX_TEMPFAIL: "try again later" — distinct from query failures
# (1), watchdog stalls (86) and signal deaths (<0)
EXIT_RESUMABLE = 75

DRAIN_ENV = "NDS_TPU_DRAIN_S"
DEFAULT_DRAIN_S = 30.0


class DrainRequested(SystemExit):
    """Raised at a query boundary once a drain was requested: unwinds
    through every ``finally`` (watchdog stop, snapshot final write,
    profiler teardown) and exits the process :data:`EXIT_RESUMABLE`."""

    def __init__(self):
        super().__init__(EXIT_RESUMABLE)


class DrainManager:
    """One drain lifecycle: install, observe, enforce the deadline."""

    def __init__(self, drain_s: float = DEFAULT_DRAIN_S,
                 run_dir: str = ".", _exit=os._exit):
        self.drain_s = max(0.1, float(drain_s))
        self.run_dir = run_dir
        self._exit = _exit
        self._requested = threading.Event()
        # set when the loop reached a boundary (or finished): the
        # deadline thread stands down instead of force-exiting
        self._finished = threading.Event()
        self._flush_hooks: list = []
        self._prev: dict = {}
        self._installed = False
        self._signum: int | None = None
        self._timer: threading.Thread | None = None

    # ------------------------------------------------------- lifecycle

    def install(self) -> "DrainManager":
        """Install the chaining handler for SIGTERM + SIGINT (main
        thread only; elsewhere the manager stays inert and the default
        signal semantics hold)."""
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev[sig] = signal.getsignal(sig)
                signal.signal(sig, self._on_signal)
            self._installed = True
        except (ValueError, OSError):
            # exotic platform: journal + supervisor still cover us
            self._prev.clear()
        return self

    def uninstall(self) -> None:
        """Restore the previous handlers (only where ours is still the
        installed one — a later installer wins) and stand the deadline
        thread down."""
        self._finished.set()
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                if signal.getsignal(sig) == self._on_signal:
                    signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._installed = False

    def add_flush_hook(self, fn) -> None:
        """Register ``fn()`` to run (best-effort, in order) on the
        force-exit path — the state a normal teardown would have
        flushed but ``os._exit`` will skip."""
        if fn not in self._flush_hooks:
            self._flush_hooks.append(fn)

    # --------------------------------------------------------- signals

    def _on_signal(self, signum, frame) -> None:
        if self._finished.is_set():
            # drain already over (or never ours): behave like the
            # handler we replaced
            self._chain(signum, frame)
            return
        first = not self._requested.is_set()
        self._signum = signum
        self._requested.set()
        if not first:
            # repeat signal: stop waiting, flush and go now
            self._force_exit("drain-repeat-signal")
            return
        name = getattr(signal.Signals(signum), "name", str(signum))
        print(f"[drain] {name} received — letting the in-flight query "
              f"finish (deadline {self.drain_s:.0f}s), will exit "
              f"{EXIT_RESUMABLE} (resumable)")
        # the PR-9 post-mortem contract, composed: bounded flight dump
        # + trace flush, safe against locks the interrupted frame holds
        from nds_tpu.obs import fleet as obs_fleet
        obs_fleet.signal_flush(f"drain:{name}")
        t = threading.Thread(target=self._deadline_watch,
                             name="nds-tpu-drain-deadline", daemon=True)
        self._timer = t
        t.start()

    def _chain(self, signum, frame) -> None:
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def _deadline_watch(self) -> None:
        if self._finished.wait(self.drain_s):
            return  # boundary reached in time: normal teardown flushes
        self._force_exit("drain-deadline")

    def _force_exit(self, reason: str) -> None:
        """Abandon the in-flight query: run the flush hooks (journal
        abort stamp, final snapshot), dump the flight ring, exit 75.
        ``os._exit`` skips every ``finally`` — everything that must
        survive is flushed HERE, explicitly."""
        self._finished.set()
        for fn in list(self._flush_hooks):
            try:
                fn()
            except Exception as exc:  # noqa: BLE001 - dying anyway
                print(f"[drain] flush hook failed: "
                      f"{type(exc).__name__}: {exc}")
        from nds_tpu.obs import fleet as obs_fleet
        obs_fleet.signal_flush(reason)
        print(f"[drain] {reason}: abandoning the in-flight query, "
              f"exiting {EXIT_RESUMABLE} (resumable)")
        self._exit(EXIT_RESUMABLE)

    # -------------------------------------------------------- boundary

    def requested(self) -> bool:
        return self._requested.is_set()

    def check_boundary(self) -> None:
        """Query-boundary checkpoint: once a drain was requested, stand
        the deadline thread down and unwind resumably."""
        if self._requested.is_set():
            self._finished.set()
            raise DrainRequested()


_MANAGER: "DrainManager | None" = None


def drain_seconds(config=None) -> float:
    """``engine.drain_s`` > ``NDS_TPU_DRAIN_S`` > 30 s default."""
    v = config.get("engine.drain_s") if config is not None else None
    if v is None:
        v = os.environ.get(DRAIN_ENV)
    try:
        return float(v) if v is not None else DEFAULT_DRAIN_S
    except (TypeError, ValueError):
        return DEFAULT_DRAIN_S


def install(drain_s: float = DEFAULT_DRAIN_S, run_dir: str = ".",
            _exit=os._exit) -> DrainManager:
    """Install the process-wide drain manager for this run (replacing
    and uninstalling any previous run's)."""
    global _MANAGER
    if _MANAGER is not None:
        _MANAGER.uninstall()
    _MANAGER = DrainManager(drain_s, run_dir, _exit=_exit).install()
    return _MANAGER


def uninstall() -> None:
    global _MANAGER
    if _MANAGER is not None:
        _MANAGER.uninstall()
        _MANAGER = None


def manager() -> "DrainManager | None":
    return _MANAGER


def requested() -> bool:
    return _MANAGER is not None and _MANAGER.requested()


def check_boundary() -> None:
    if _MANAGER is not None:
        _MANAGER.check_boundary()
