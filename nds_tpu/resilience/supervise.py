"""Supervised subprocess stream fleets: liveness, kill, restart-once.

The throughput drivers fan one power-run subprocess out per stream
(`nds/nds-throughput:23` analog). Before this module the parent just
``wait()``-ed: a child that hung (stuck compile, wedged collective,
injected chaos) held the whole round forever, and a child that died
was a bare failure count with no post-mortem. The supervisor closes
both gaps, using only artifacts the stack already emits:

- **Liveness** comes from each child's metrics-snapshot file (the
  ``NDS_TPU_METRICS_SNAP`` emitter, which embeds the heartbeat
  registry of resilience/watchdog.py): effective heartbeat age =
  (now - file mtime) + the youngest in-file heartbeat age. The file
  mtime alone is NOT liveness — the snapshot daemon thread keeps
  writing while the query loop hangs; the heartbeat ages inside are
  what stop advancing.

- **Kill** is two-layered. Children are armed with
  ``NDS_TPU_WATCHDOG=stall_s:kill`` so a hung-but-responsive child
  dumps its own all-thread stall report and exits ``EXIT_STALLED``;
  the parent is the backstop for fully wedged children — past
  ``2 * stall_s`` of heartbeat silence it escalates SIGTERM → grace →
  SIGKILL and writes a supervisor-side ``stall-<stream>.json``.

- **Restart budget** — a stream that died mid-run (stall exit, signal,
  crash) restarts at most ``max_restarts`` times (default once;
  ``--max_restarts`` / bench YAML ``watchdog.max_restarts`` raise it),
  resuming from its last completed query (tracked in a per-stream
  mini-journal, ``<name>_journal.json``, fed by the snapshot
  progress). The restarted incarnation's ``NDS_TPU_STREAM`` is
  ``<name>#r1``, so seeded chaos schedules scoped to ``<name>`` hit
  only the first incarnation — deterministic chaos replay extends
  across restarts. A stream whose snapshot shows every query completed
  is never restarted (the reference exits 1 on query failures AFTER
  finishing the stream; re-running it would double-count).

- **Resumable exits** — a child that exits
  :data:`~nds_tpu.resilience.drain.EXIT_RESUMABLE` (75) drained
  gracefully after a preemption signal (resilience/drain.py): it is
  relaunched from its last completed query WITHOUT charging the
  restart budget (counted separately as ``resumes``, capped by
  ``max_resumes`` so a pathological instant-preempt loop still
  terminates).

Exit codes, signals, stalls, restarts and resumes land in
``throughput_summary.json`` (and the returned summary dict) instead of
a bare failure count — including ``skipped_queries``, the exact
statements a stream that gave up never ran, so a degraded round's gap
is enumerable instead of a count. ``stream_restarts_total`` /
``stream_resumes_total`` / ``stream_stalls_total`` count fleet-wide.
Metrics: README "Resilience".
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import time
from dataclasses import dataclass, field
from typing import Callable

from nds_tpu.io.integrity import write_json_atomic
from nds_tpu.resilience.drain import EXIT_RESUMABLE
from nds_tpu.resilience.watchdog import (
    EXIT_STALLED, STREAM_ENV, WATCHDOG_ENV,
)

SUMMARY_NAME = "throughput_summary.json"

# multi-statement templates split into query15_part1/2/3-style groups
# whose parts share in-process state (NDS-H q15's CREATE VIEW / SELECT
# / DROP VIEW): a restart must never resume MID-group
_PART_RE = re.compile(r"^(?P<base>.+)_part(?P<n>\d+)$")


def resume_index(queries: list, completed: int) -> int:
    """Where a restarted incarnation should resume: ``completed``,
    snapped BACK to the start of a split part group when the boundary
    falls mid-group — re-running a completed part is idempotent, but
    skipping part1's CREATE VIEW deterministically fails part2."""
    i = min(completed, len(queries))
    while 0 < i < len(queries):
        m = _PART_RE.match(str(queries[i]))
        if m and int(m.group("n")) > 1:
            i -= 1
            continue
        break
    return i


@dataclass
class StreamSpec:
    """One supervised stream: how to (re)launch it and what it runs.

    ``make_cmd(incarnation, remaining)`` builds the argv — on restart
    ``remaining`` is the ordered list of query names still to run (the
    caller appends its driver's ``--query_subset`` flag); ``None``
    means the full stream."""
    name: str
    make_cmd: Callable
    hb_path: str
    queries: list = field(default_factory=list)
    env: dict | None = None


def fold_child_snapshot(st: dict) -> None:
    """Fold a child's latest metrics snapshot into its supervisor
    state: absolute completed-query count and effective heartbeat age
    ((now - file mtime) + youngest in-file age). Shared by the
    throughput StreamSupervisor and the serve-fleet
    ReplicaSupervisor — one liveness definition, two fleets."""
    path = st["spec"].hb_path
    try:
        mtime = os.stat(path).st_mtime
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return  # not written yet / mid-rename: keep previous state
    if mtime < st["launched_at"]:
        # stale snapshot from a previous incarnation: trusting its
        # ages would kill the fresh restart before its first write
        return
    prog = doc.get("progress") or {}
    done_now = int(prog.get("queries_completed") or 0)
    st["completed"] = st["base_completed"] + done_now
    st["inc_total"] = prog.get("queries_total")
    st["inc_completed"] = done_now
    hbs = doc.get("heartbeats") or {}
    if hbs:
        st["saw_heartbeat"] = True
        youngest = min(h.get("age_s", 0.0) for h in hbs.values())
        st["hb_age"] = (time.time() - mtime) + youngest
        st["current"] = next(
            (h.get("query") for h in hbs.values()
             if h.get("query")), None)


class StreamSupervisor:
    """Launch, watch, kill, restart-once, summarize."""

    def __init__(self, specs: list[StreamSpec], out_dir: str,
                 stall_s: float | None = None, poll_s: float = 0.5,
                 grace_s: float = 5.0, max_restarts: int = 1,
                 startup_grace_s: float | None = None,
                 max_resumes: int = 3):
        self.specs = specs
        self.out_dir = out_dir
        self.stall_s = stall_s
        self.poll_s = poll_s
        self.grace_s = grace_s
        self.max_restarts = max_restarts
        # graceful-drain exits (75) relaunch without charging the
        # restart budget, but still bounded: an environment that
        # preempts instantly forever must not spin
        self.max_resumes = max_resumes
        # before the first heartbeat lands (interpreter + jax import +
        # warehouse load) silence is startup, not a stall
        self.startup_grace_s = (
            startup_grace_s if startup_grace_s is not None
            else max(30.0, 4.0 * (stall_s or 0.0)))

    # ------------------------------------------------------- lifecycle

    def _launch(self, st: dict, remaining: list | None) -> None:
        spec = st["spec"]
        inc = st["incarnation"]
        env = dict(spec.env if spec.env is not None else os.environ)
        env[STREAM_ENV] = (spec.name if inc == 0
                           else f"{spec.name}#r{inc}")
        if self.stall_s:
            # hb emit interval well inside the stall budget, and the
            # child-side watchdog armed to self-report + self-kill
            from nds_tpu.obs.snapshot import SNAP_ENV
            interval = max(0.2, min(1.0, self.stall_s / 4.0))
            env[SNAP_ENV] = f"{spec.hb_path}:{interval}"
            env[WATCHDOG_ENV] = f"{self.stall_s}:kill"
        cmd = spec.make_cmd(inc, remaining)
        st["proc"] = subprocess.Popen(cmd, env=env)
        st["launched_at"] = time.time()
        st["saw_heartbeat"] = False
        st.pop("hb_age", None)

    def _read_hb(self, st: dict) -> None:
        fold_child_snapshot(st)

    def _stalled(self, st: dict, now: float) -> str | None:
        if not self.stall_s:
            return None
        if st["saw_heartbeat"]:
            # parent is the BACKSTOP: the child's own watchdog gets the
            # first stall_s window to self-report and exit
            age = st.get("hb_age")
            if age is not None and age > 2.0 * self.stall_s:
                return f"heartbeat silent {age:.1f}s"
            return None
        if now - st["launched_at"] > self.startup_grace_s:
            return (f"no heartbeat within "
                    f"{self.startup_grace_s:.0f}s of launch")
        return None

    def _kill(self, st: dict, reason: str) -> None:
        proc = st["proc"]
        proc.terminate()
        try:
            proc.wait(timeout=self.grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        self._record_stall(st, reason, source="supervisor")

    def _record_stall(self, st: dict, reason: str, source: str) -> None:
        from nds_tpu.obs import metrics as obs_metrics
        obs_metrics.counter("stream_stalls_total").inc()
        rec = {"stream": st["spec"].name,
               "incarnation": st["incarnation"],
               "query": st.get("current"),
               "age_s": round(st.get("hb_age") or 0.0, 3),
               "reason": reason, "source": source,
               "ts": time.time()}
        st["stalls"].append(rec)
        write_json_atomic(
            os.path.join(self.out_dir,
                         f"stall-{st['spec'].name}.json"), rec)

    def _journal(self, st: dict) -> None:
        # only on change: a multi-hour round must not rewrite N journal
        # files twice a second for nothing
        state = (st["completed"], st["incarnation"], st["restarts"])
        if st.get("journaled") == state:
            return
        st["journaled"] = state
        write_json_atomic(
            os.path.join(self.out_dir,
                         f"{st['spec'].name}_journal.json"),
            {"completed": st["completed"],
             "incarnation": st["incarnation"],
             "restarts": st["restarts"],
             "queries_total": len(st["spec"].queries) or None})

    # ------------------------------------------------------------- run

    def run(self) -> tuple[float, list, dict]:
        """Returns (elapse_s, final exit code per stream, summary).
        The summary is also written to ``<out_dir>/throughput_summary
        .json``."""
        os.makedirs(self.out_dir, exist_ok=True)
        start = time.time()
        states = []
        for spec in self.specs:
            st = {"spec": spec, "incarnation": 0, "exit_codes": [],
                  "signals": [], "stalls": [], "restarts": 0,
                  "resumes": 0, "completed": 0, "base_completed": 0,
                  "saw_heartbeat": False, "done": False}
            states.append(st)
            self._launch(st, None)
        while any(not st["done"] for st in states):
            time.sleep(self.poll_s)
            now = time.time()
            for st in states:
                if st["done"]:
                    continue
                self._read_hb(st)
                self._journal(st)
                rc = st["proc"].poll()
                if rc is None:
                    reason = self._stalled(st, now)
                    if reason is not None:
                        self._kill(st, reason)
                        rc = st["proc"].returncode
                    else:
                        continue
                self._read_hb(st)  # final progress before deciding
                st["ended_at"] = now
                st["exit_codes"].append(rc)
                if rc is not None and rc < 0:
                    st["signals"].append(-rc)
                if rc == EXIT_STALLED:
                    self._record_stall(
                        st, "child watchdog exit", source="watchdog")
                if rc == 0 or self._finished_all(st):
                    st["done"] = True
                    continue
                # a graceful drain (exit 75, resilience/drain.py) is a
                # RESUME, not a failure: relaunch from the last
                # completed query without charging the restart budget
                resumable = (rc == EXIT_RESUMABLE
                             and st["resumes"] < self.max_resumes)
                if not resumable and st["restarts"] >= self.max_restarts:
                    st["done"] = True
                    continue
                from nds_tpu.obs import metrics as obs_metrics
                if resumable:
                    obs_metrics.counter("stream_resumes_total").inc()
                    st["resumes"] += 1
                else:
                    obs_metrics.counter("stream_restarts_total").inc()
                    st["restarts"] += 1
                st["incarnation"] += 1
                if st["spec"].queries:
                    start_q = resume_index(st["spec"].queries,
                                           st["completed"])
                    remaining = st["spec"].queries[start_q:]
                else:
                    start_q, remaining = 0, None
                st["base_completed"] = start_q
                st["completed"] = start_q
                print(f"[supervise] restarting {st['spec'].name} "
                      f"(rc={rc}) from query #{start_q}")
                self._launch(st, remaining)
        # throughput elapse is max(child end) - min(start), the
        # reference's Ttt window — NOT the poll loop's own wall time
        # (which would bill summary writing and up to one poll_s of
        # detection latency to the benchmark metric)
        elapse = max((st.get("ended_at", start) for st in states),
                     default=start) - start
        codes = [self._final_code(st) for st in states]
        summary = {
            "elapse_s": round(elapse, 3),
            "stall_s": self.stall_s,
            "streams": {
                st["spec"].name: self._stream_summary(st, code)
                for st, code in zip(states, codes)},
        }
        write_json_atomic(os.path.join(self.out_dir, SUMMARY_NAME),
                          summary)
        return elapse, codes, summary

    def _stream_summary(self, st: dict, code: int) -> dict:
        out = {
            "exit_codes": st["exit_codes"],
            "signals": st["signals"],
            "restarts": st["restarts"],
            "resumes": st["resumes"],
            "stalls": st["stalls"],
            "completed": st["completed"],
            "queries_total": len(st["spec"].queries) or None,
            "degraded": bool(st["restarts"] or st["stalls"]
                             or st["resumes"]),
            "final_code": code,
        }
        # a degraded stream that gave up names EXACTLY the statements
        # it never completed — a gap in a throughput round must be
        # enumerable, not a bare count
        queries = st["spec"].queries
        if queries and code != 0 and not self._finished_all(st):
            out["skipped_queries"] = [
                str(q) for q in queries[min(st["completed"],
                                            len(queries)):]]
        return out

    @staticmethod
    def _finished_all(st: dict) -> bool:
        """The incarnation's snapshot says every query ran: the stream
        FINISHED (possibly with query failures, the reference's exit-1
        contract) — restarting would re-run completed work."""
        total = st.get("inc_total")
        return (total is not None
                and st.get("inc_completed", 0) >= total)

    @staticmethod
    def _final_code(st: dict) -> int:
        rc = st["exit_codes"][-1] if st["exit_codes"] else 1
        return 0 if rc == 0 else rc


def _signal_name(num: int) -> str:
    try:
        return signal.Signals(num).name
    except ValueError:
        return f"SIG{num}"


@dataclass
class ReplicaSpec:
    """One supervised serve replica: ``make_cmd(incarnation)`` builds
    the argv (typically ``python -m nds_tpu.serve.replica ...``);
    ``hb_path`` is its metrics-snapshot liveness file, ``announce_path``
    the endpoint file the router watches."""
    name: str
    make_cmd: Callable
    hb_path: str
    announce_path: str
    env: dict | None = None


class ReplicaSupervisor:
    """Fleet mode of the supervisor: long-RUNNING children instead of
    run-to-completion streams.

    The throughput StreamSupervisor's ``run()`` blocks until every
    child finishes; serve replicas never finish, so this variant polls
    from a background thread and exposes a control surface instead:

    - ``drain(name)`` — SIGTERM one replica; it drains under
      ``engine.drain_s`` and exits 75, which relaunches WARM (shared
      AOT store) without charging the restart budget (``max_resumes``
      bounds a pathological preempt loop, exactly like stream resume).
    - ``kill(name, sig)`` — chaos hook (ndsload ``--kill`` schedules):
      a SIGKILLed replica restarts under ``max_restarts``.
    - membership hooks — ``on_membership(up=..., down=...)``: the
      router ejects on ``down(name, reason)`` and HEALTH-PROBES (not
      trusts) on ``up(name, incarnation)`` before re-admitting.

    Liveness is the same two-layer contract as streams: children armed
    with ``NDS_TPU_WATCHDOG=stall_s:kill`` self-report + exit 86; the
    parent backstop escalates past ``2 * stall_s`` of heartbeat
    silence (``fold_child_snapshot`` ages). ``NDS_TPU_REPLICA`` carries
    the replica id into the child so responses/summaries/metrics are
    attributed; ``NDS_TPU_STREAM=<name>#rN`` keeps seeded chaos
    schedules incarnation-scoped."""

    def __init__(self, specs: "list[ReplicaSpec]", out_dir: str,
                 stall_s: float | None = None, poll_s: float = 0.25,
                 grace_s: float = 10.0, max_restarts: int = 2,
                 max_resumes: int = 3,
                 startup_grace_s: float | None = None):
        self.specs = specs
        self.out_dir = out_dir
        self.stall_s = stall_s
        self.poll_s = poll_s
        self.grace_s = grace_s
        self.max_restarts = max_restarts
        self.max_resumes = max_resumes
        self.startup_grace_s = (
            startup_grace_s if startup_grace_s is not None
            else max(30.0, 4.0 * (stall_s or 0.0)))
        self._states: "dict[str, dict]" = {}
        self._up_hooks: list = []
        self._down_hooks: list = []
        from nds_tpu.analysis import locksan
        # the poll thread and the router's control calls
        # (drain/kill/stop) mutate child state concurrently
        self._lock = locksan.lock("resilience.ReplicaSupervisor._lock")
        self._stop = None  # threading.Event once started
        self._thread = None

    # ---------------------------------------------------- membership

    def on_membership(self, up=None, down=None) -> None:
        """Register ``up(name, incarnation)`` / ``down(name, reason)``
        callbacks (called from the poll thread; keep them quick)."""
        if up is not None:
            self._up_hooks.append(up)
        if down is not None:
            self._down_hooks.append(down)

    def _emit(self, hooks: list, *a) -> None:
        for fn in hooks:
            try:
                fn(*a)
            except Exception as exc:  # noqa: BLE001 - never kill polls
                print(f"[fleet] membership hook failed: "
                      f"{type(exc).__name__}: {exc}")

    # ----------------------------------------------------- lifecycle

    def _launch(self, st: dict) -> None:
        spec = st["spec"]
        inc = st["incarnation"]
        env = dict(spec.env if spec.env is not None else os.environ)
        env[STREAM_ENV] = (spec.name if inc == 0
                           else f"{spec.name}#r{inc}")
        env["NDS_TPU_REPLICA"] = spec.name
        if self.stall_s:
            from nds_tpu.obs.snapshot import SNAP_ENV
            interval = max(0.2, min(1.0, self.stall_s / 4.0))
            env[SNAP_ENV] = f"{spec.hb_path}:{interval}"
            env[WATCHDOG_ENV] = f"{self.stall_s}:kill"
        st["proc"] = subprocess.Popen(spec.make_cmd(inc), env=env)
        st["launched_at"] = time.time()
        st["saw_heartbeat"] = False
        st.pop("hb_age", None)

    def start(self) -> "ReplicaSupervisor":
        import threading
        os.makedirs(self.out_dir, exist_ok=True)
        with self._lock:
            for spec in self.specs:
                st = {"spec": spec, "incarnation": 0,
                      "exit_codes": [], "signals": [], "stalls": [],
                      "restarts": 0, "resumes": 0, "completed": 0,
                      "base_completed": 0, "saw_heartbeat": False,
                      "failed": False}
                self._states[spec.name] = st
                self._launch(st)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._poll_loop, name="nds-tpu-fleet-supervisor",
            daemon=True)
        self._thread.start()
        return self

    def add_replica(self, spec: ReplicaSpec) -> None:
        """Scale-out: launch one more replica into a RUNNING fleet.
        A late joiner warms from the shared AOT store (zero compiles
        when the fleet already paid them) and is health-probed — not
        trusted — by the router before taking traffic."""
        with self._lock:
            if spec.name in self._states:
                raise ValueError(
                    f"replica {spec.name!r} already in the fleet")
            self.specs.append(spec)
            st = {"spec": spec, "incarnation": 0,
                  "exit_codes": [], "signals": [], "stalls": [],
                  "restarts": 0, "resumes": 0, "completed": 0,
                  "base_completed": 0, "saw_heartbeat": False,
                  "failed": False}
            self._states[spec.name] = st
            self._launch(st)
        self._emit(self._up_hooks, spec.name, 0)

    def stop(self) -> dict:
        """Terminate the fleet (SIGTERM → grace → SIGKILL) and return
        the summary (also written to ``<out_dir>/fleet_summary.json``)."""
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        with self._lock:
            states = list(self._states.values())
        for st in states:
            proc = st.get("proc")
            if proc is not None and proc.poll() is None:
                proc.terminate()
        for st in states:
            proc = st.get("proc")
            if proc is None:
                continue
            try:
                proc.wait(timeout=self.grace_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        summary = self.summary()
        write_json_atomic(
            os.path.join(self.out_dir, "fleet_summary.json"), summary)
        return summary

    def drain(self, name: str) -> None:
        """SIGTERM one replica: graceful drain → exit 75 → warm
        resume (not charged to the restart budget)."""
        self.kill(name, signal.SIGTERM)

    def kill(self, name: str, sig: int = signal.SIGKILL) -> None:
        """Chaos/control hook: deliver ``sig`` to a replica's current
        incarnation (no-op if it is already down)."""
        with self._lock:
            st = self._states.get(name)
            proc = st.get("proc") if st else None
        if proc is not None and proc.poll() is None:
            proc.send_signal(sig)

    # -------------------------------------------------------- polling

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            now = time.time()
            with self._lock:
                states = list(self._states.values())
            for st in states:
                try:
                    self._poll_one(st, now)
                except Exception as exc:  # noqa: BLE001 - keep polling
                    print(f"[fleet] poll({st['spec'].name}) failed: "
                          f"{type(exc).__name__}: {exc}")

    def _poll_one(self, st: dict, now: float) -> None:
        from nds_tpu.obs import metrics as obs_metrics
        if st["failed"]:
            return
        fold_child_snapshot(st)
        rc = st["proc"].poll()
        if rc is None:
            reason = self._stalled(st, now)
            if reason is None:
                return
            # parent backstop for a fully wedged child: the child's
            # own kill-action watchdog had its stall_s window first
            self._emit(self._down_hooks, st["spec"].name,
                       f"stall: {reason}")
            obs_metrics.counter("fleet_replica_stalls_total").inc()
            st["stalls"].append({"reason": reason, "ts": now})
            proc = st["proc"]
            proc.terminate()
            try:
                proc.wait(timeout=self.grace_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            rc = proc.returncode
        else:
            self._emit(self._down_hooks, st["spec"].name,
                       f"exit {rc}")
        st["exit_codes"].append(rc)
        if rc is not None and rc < 0:
            st["signals"].append(-rc)
        if rc == EXIT_STALLED:
            obs_metrics.counter("fleet_replica_stalls_total").inc()
            st["stalls"].append({"reason": "child watchdog exit",
                                 "ts": now})
        if rc == 0:
            # operator stop (SIGINT drain): intended departure, no
            # relaunch
            st["failed"] = True
            return
        resumable = (rc == EXIT_RESUMABLE
                     and st["resumes"] < self.max_resumes)
        if not resumable and st["restarts"] >= self.max_restarts:
            st["failed"] = True
            print(f"[fleet] {st['spec'].name} gave up (rc={rc}, "
                  f"restarts={st['restarts']})")
            return
        if resumable:
            obs_metrics.counter("fleet_replica_resumes_total").inc()
            st["resumes"] += 1
        else:
            obs_metrics.counter("fleet_replica_restarts_total").inc()
            st["restarts"] += 1
        st["incarnation"] += 1
        print(f"[fleet] relaunching {st['spec'].name} (rc={rc}) "
              f"as incarnation {st['incarnation']}")
        self._launch(st)
        self._emit(self._up_hooks, st["spec"].name,
                   st["incarnation"])

    def _stalled(self, st: dict, now: float) -> "str | None":
        if not self.stall_s:
            return None
        if st["saw_heartbeat"]:
            age = st.get("hb_age")
            if age is not None and age > 2.0 * self.stall_s:
                return f"heartbeat silent {age:.1f}s"
            return None
        if now - st["launched_at"] > self.startup_grace_s:
            return (f"no heartbeat within "
                    f"{self.startup_grace_s:.0f}s of launch")
        return None

    # -------------------------------------------------------- readout

    def summary(self) -> dict:
        with self._lock:
            return {"replicas": {
                name: {"incarnation": st["incarnation"],
                       "exit_codes": list(st["exit_codes"]),
                       "signals": list(st["signals"]),
                       "restarts": st["restarts"],
                       "resumes": st["resumes"],
                       "stalls": list(st["stalls"]),
                       "completed": st["completed"],
                       "failed": st["failed"]}
                for name, st in self._states.items()}}


def describe_summary(summary: dict) -> str:
    """One human line per stream for driver stdout."""
    lines = []
    for name, s in summary.get("streams", {}).items():
        bits = [f"rc={s['final_code']}"]
        if s["restarts"]:
            bits.append(f"restarts={s['restarts']}")
        if s.get("resumes"):
            bits.append(f"resumes={s['resumes']}")
        if s.get("skipped_queries"):
            bits.append(f"skipped={len(s['skipped_queries'])}")
        if s["stalls"]:
            bits.append(f"stalls={len(s['stalls'])}")
        if s["signals"]:
            bits.append("signals="
                        + ",".join(_signal_name(x)
                                   for x in s["signals"]))
        if s["degraded"]:
            bits.append("DEGRADED")
        lines.append(f"  {name}: {' '.join(bits)}")
    return "\n".join(lines)
