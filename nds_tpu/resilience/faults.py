"""Seeded, deterministic fault injection at named sites.

Chaos testing for the harness itself: the engine/parallel/io layers
call ``fault_point(site, **info)`` inline at their hazard points, and a
schedule — the ``NDS_TPU_FAULTS`` env var or a programmatic
``install()`` — decides which calls raise, delay, or pass through.
Unset, a fault point is one dict lookup and a string compare (the same
zero-cost-when-disabled contract as ``nds_tpu/obs/trace.py``).

Registered sites (the call sites live inline in the layer they test):

- ``plan``            Session.plan (parse+plan front door)
- ``device.execute``  every executor's execute/execute_async entry
                      (CPU oracle included, so chaos runs need no chip)
- ``exchange``        the distributed all_to_all shuffle (trace time)
- ``io.read``         warehouse table reads (csv/parquet/raw); the
                      call passes ``paths`` so ``corrupt`` can bite.
                      Also fires per STAGED CHUNK in the chunked
                      engine's phase-A loops (engine/pipeline_io.py)
                      — on the prefetch worker thread when depth > 0,
                      with the submitting thread's context
                      republished, so an injected fault surfaces at
                      the consumer in chunk order with classification
                      and retry semantics identical to the serial
                      path
- ``stream.query``    per-query dispatch in the stream loops (the
                      power loop fires it per ATTEMPT inside the retry
                      policy; the in-process throughput loop fires it
                      at dispatch). Supervised subprocess streams add
                      ``stream=<name>`` (``<name>#rN`` on restart) to
                      the context, so a schedule can target one stream
                      — or one incarnation — of a fleet

Schedule syntax (comma-separated entries)::

    NDS_TPU_FAULTS="device.execute:oom@q5,io.read:delay=0.2@*"

    entry := site ":" kind ["=" param] ["*" times] ["~" prob] "@" scope

- ``kind``   ``oom`` (raises InjectedOOM, classified transient),
             ``fault`` (generic transient), ``deterministic`` (never
             retried), ``delay`` (sleeps ``param`` seconds),
             ``hang`` (interruptible dead-stop of ``param`` seconds at
             the site — nothing beats, nothing returns — so watchdog /
             supervisor hang detection is deterministically testable;
             ``interrupt_hangs()`` releases every pending hang),
             ``corrupt`` (flips one byte mid-file of the first path in
             the call's ``paths`` context — registered at ``io.read`` —
             so digest verification (io/integrity.py) is testable
             end-to-end; the file on disk IS mutated)
- ``times``  how many matching calls fire (default 1 for raising and
             mutating kinds — so one retry succeeds / one file breaks —
             unlimited for ``delay``)
- ``prob``   per-match firing probability in [0,1] (default 1); drawn
             from a counter-keyed RNG seeded by ``NDS_TPU_FAULT_SEED``,
             so a chaos run replays EXACTLY from its seed
- ``scope``  fnmatch pattern over the call's context values (the power
             loop publishes the current query name via ``context()``);
             ``q5`` also matches ``query5``, ``*`` matches everything

Every fired fault increments the ``faults_injected_total`` metrics
counter with the site recorded on the exception, so chaos runs are
auditable from the report JSON alone.
"""

from __future__ import annotations

import fnmatch
import os
import random
import re
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

FAULTS_ENV = "NDS_TPU_FAULTS"
SEED_ENV = "NDS_TPU_FAULT_SEED"

SITES = ("plan", "device.execute", "exchange", "io.read", "stream.query",
         "dml.apply", "store.commit")


class InjectedFault(RuntimeError):
    """Base class for every injected failure (always carries its site
    so reports/classifiers can tell chaos from organic errors)."""

    def __init__(self, site: str, msg: str):
        super().__init__(msg)
        self.site = site


class InjectedTransientFault(InjectedFault):
    """Injected failure the retry classifier treats as transient."""


class InjectedOOM(InjectedTransientFault):
    """Injected device-memory exhaustion; the message deliberately
    carries RESOURCE_EXHAUSTED so generic OOM classification (the one
    real jaxlib errors hit) covers it too."""


class InjectedDeterministicFault(InjectedFault):
    """Injected failure that must NEVER be retried (the planner-bug
    analog)."""


_ENTRY_RE = re.compile(
    r"^(?P<site>[a-z_.]+):(?P<kind>[a-z]+)"
    r"(?:=(?P<param>[0-9.]+))?"
    r"(?:\*(?P<times>\d+))?"
    r"(?:~(?P<prob>[0-9.]+))?"
    r"@(?P<scope>.+)$")

_KINDS = ("oom", "fault", "deterministic", "delay", "hang", "corrupt")


@dataclass
class FaultSpec:
    """One parsed schedule entry."""
    site: str
    kind: str
    scope: str
    param: float | None = None
    times: int | None = 1       # None = unlimited
    prob: float = 1.0
    index: int = 0              # position in the schedule (RNG keying)
    fired: int = 0
    matched: int = 0


def parse_schedule(text: str) -> list[FaultSpec]:
    specs: list[FaultSpec] = []
    for i, raw in enumerate(e.strip() for e in text.split(",")):
        if not raw:
            continue
        m = _ENTRY_RE.match(raw)
        if m is None:
            raise ValueError(
                f"bad {FAULTS_ENV} entry {raw!r} (expected "
                f"site:kind[=param][*times][~prob]@scope)")
        site, kind = m.group("site"), m.group("kind")
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r} (known: {', '.join(SITES)})")
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} (known: {', '.join(_KINDS)})")
        times = m.group("times")
        specs.append(FaultSpec(
            site=site, kind=kind, scope=m.group("scope"),
            param=float(m.group("param")) if m.group("param") else None,
            times=(int(times) if times is not None
                   else (None if kind == "delay" else 1)),
            prob=float(m.group("prob")) if m.group("prob") else 1.0,
            index=i))
    return specs


def _scope_matches(scope: str, ctx: dict) -> bool:
    if scope == "*":
        return True
    patterns = [scope]
    # `q5` is the documented shorthand for NDS query names (`query5`)
    m = re.match(r"^q(\d.*)$", scope)
    if m:
        patterns.append("query" + m.group(1))
    return any(fnmatch.fnmatchcase(str(v), p)
               for v in ctx.values() for p in patterns)


@dataclass
class FaultPlan:
    """A parsed schedule bound to a seed; owns firing bookkeeping."""
    specs: list = field(default_factory=list)
    seed: int = 0

    def fire(self, site: str, ctx: dict) -> None:
        for spec in self.specs:
            if spec.site != site or not _scope_matches(spec.scope, ctx):
                continue
            if spec.times is not None and spec.fired >= spec.times:
                continue
            spec.matched += 1
            if spec.prob < 1.0:
                # counter-keyed draw: replaying the same schedule+seed
                # over the same call sequence reproduces bit-for-bit
                # (bytes seeding is version-stable; tuple seeding would
                # go through the salted str hash)
                key = f"{self.seed}:{site}:{spec.index}:{spec.matched}"
                if random.Random(key.encode()).random() >= spec.prob:
                    continue
            spec.fired += 1
            self._act(spec, site, ctx)

    @staticmethod
    def _act(spec: FaultSpec, site: str, ctx: dict) -> None:
        from nds_tpu.obs import metrics as obs_metrics
        obs_metrics.counter("faults_injected_total").inc()
        where = f"site={site}" + (
            f" query={ctx['query']}" if ctx.get("query") else "")
        if spec.kind == "delay":
            time.sleep(spec.param or 0.0)
            return
        if spec.kind == "hang":
            # dead-stop: no heartbeat, no return — exactly what a stuck
            # compile or wedged collective looks like from outside. The
            # sleep is sliced so interrupt_hangs() (and tests) can
            # release it without killing the process
            end = time.monotonic() + (spec.param or 0.0)
            while (time.monotonic() < end
                   and not _hang_interrupt.wait(0.05)):
                pass
            return
        if spec.kind == "corrupt":
            _flip_byte(ctx)
            return
        if spec.kind == "oom":
            raise InjectedOOM(
                site, f"injected RESOURCE_EXHAUSTED: out of memory "
                      f"({where})")
        if spec.kind == "deterministic":
            raise InjectedDeterministicFault(
                site, f"injected deterministic fault ({where})")
        raise InjectedTransientFault(
            site, f"injected transient fault ({where})")


def _flip_byte(ctx: dict) -> None:
    """``corrupt`` kind: XOR one byte in the middle of the first
    existing non-empty file in the call's ``paths`` context (the
    ``io.read`` sites pass the file list). The mutation is real and
    persistent — the point is that the NEXT digest verification must
    catch it."""
    for p in ctx.get("paths") or ():
        try:
            size = os.path.getsize(p)
        except OSError:
            continue
        if size == 0:
            continue
        pos = size // 2
        with open(p, "r+b") as f:
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0xFF]))
        return
    raise ValueError(
        "corrupt fault fired at a site with no 'paths' context "
        "(register it at io.read, or pass paths=[...])")


# programmatic plan (tests / chaos_check) beats the env-derived one;
# the env plan caches on the (schedule, seed) STRINGS so fault_point
# stays two dict lookups + a compare when nothing changed (and a no-op
# when unset) — keying on the schedule alone would silently ignore a
# changed seed and leak fired-counts across in-process runs
_installed: FaultPlan | None = None
_env_cache: tuple[tuple | None, FaultPlan | None] = (None, None)
_suppressed = 0
_ctx = threading.local()
_hang_interrupt = threading.Event()


def interrupt_hangs() -> None:
    """Release every in-flight (and future) ``hang`` fault — the
    in-process escape hatch a test or watchdog action can pull without
    killing the interpreter. ``clear()`` re-arms hangs."""
    _hang_interrupt.set()


def install(schedule: str, seed: int = 0) -> FaultPlan:
    """Activate a schedule programmatically (wins over the env var).
    Returns the plan so callers can inspect firing counts."""
    global _installed
    _installed = FaultPlan(parse_schedule(schedule), seed)
    return _installed


def clear() -> None:
    """Drop the programmatic plan AND the env cache (tests); re-arms
    the hang kind after an ``interrupt_hangs()``."""
    global _installed, _env_cache
    _installed = None
    _env_cache = (None, None)
    _hang_interrupt.clear()


def _current_plan() -> FaultPlan | None:
    if _installed is not None:
        return _installed
    global _env_cache
    text = os.environ.get(FAULTS_ENV)
    if not text:
        return None
    key = (text, os.environ.get(SEED_ENV, "0"))
    if key != _env_cache[0]:
        _env_cache = (key, FaultPlan(parse_schedule(text),
                                     int(key[1])))
    return _env_cache[1]


@contextmanager
def context(**kv):
    """Publish call-site context (e.g. the current query name) to every
    fault_point fired inside the block; thread-local, nestable."""
    prev = getattr(_ctx, "d", {})
    _ctx.d = {**prev, **kv}
    try:
        yield
    finally:
        _ctx.d = prev


def current_context() -> dict:
    """Read-only copy of the active thread-local context (the query /
    stream names the loops publish via ``context()``). The scheduler
    keys its memory-HWM history and reschedule records on the query
    name without threading it through every executor signature."""
    return dict(getattr(_ctx, "d", {}))


@contextmanager
def suppress():
    """Disable firing inside the block (warmup passes must not consume
    a timed query's fault budget)."""
    global _suppressed
    _suppressed += 1
    try:
        yield
    finally:
        _suppressed -= 1


def fault_point(site: str, **info) -> None:
    """Inline injection site: no-op unless an active schedule matches.

    ``info`` extends the thread-local context for scope matching (e.g.
    ``fault_point("io.read", table=name)``)."""
    plan = _current_plan()
    if plan is None or _suppressed:
        return
    plan.fire(site, {**getattr(_ctx, "d", {}), **info})
