"""Heartbeat watchdog: hang detection for unattended benchmark runs.

PR 3 made *failing* queries survivable, but a query that HANGS — a
stuck XLA compile, a wedged collective, a stalled subprocess stream —
previously stalled the whole benchmark silently: the per-query deadline
is checked around attempts, so a call that never returns was never
caught. Execution-template-style systems (PAPERS.md) keep long fan-out
runs live with cheap control-plane heartbeats; this module is that
control plane for one process:

- **Heartbeats** — the power loop, every executor, the exchange and the
  chunk loops call ``beat(unit, query=..., phase=..., attempt=...)`` at
  their progress points. A beat is a timestamped dict store under one
  lock: always on, no config needed, cheap enough for per-chunk
  granularity. ``snapshot_heartbeats()`` renders the registry as
  ``{unit: {age_s, query, phase, attempt, count}}`` — the metrics
  snapshot emitter (obs/snapshot.py) embeds it in every live snapshot,
  which is how the *parent-side* stream supervisor
  (resilience/supervise.py) observes a child's liveness from outside.

- **Watchdog** — a daemon thread (config ``engine.watchdog.stall_s`` /
  ``engine.watchdog.action``, or ``NDS_TPU_WATCHDOG=stall_s[:action]``
  for subprocess fleets) that alarms when the NEWEST beat across all
  units is older than ``stall_s`` — any progress anywhere re-arms, so a
  long query whose executor still beats per chunk is never a false
  positive. On a stall it dumps every thread's stack plus the live
  metrics snapshot to ``stall-<query>.json`` in the run dir, increments
  ``watchdog_stalls_total``, and — ``action=kill``, the subprocess-
  stream setting — exits the process with :data:`EXIT_STALLED` so the
  supervisor can restart the stream instead of waiting forever. Each
  stall reports once; a new beat after the dump re-arms the alarm.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

from nds_tpu.analysis import locksan

WATCHDOG_ENV = "NDS_TPU_WATCHDOG"
# stream supervisors name each child's unit through this env var (the
# power loop falls back to "power-<suite>"); restarted incarnations get
# a "#rN" suffix so seeded chaos schedules can target one incarnation
STREAM_ENV = "NDS_TPU_STREAM"

# exit code a kill-action watchdog terminates with: distinguishable
# from query failures (1) and signals (<0) in the supervisor's summary
EXIT_STALLED = 86

_lock = locksan.lock("resilience.watchdog._lock")
_beats: dict[str, dict] = {}

# stall hooks (obs/fleet.py flight-recorder dump, obs/profile.py
# on-stall XLA capture): called from the watchdog thread while the
# stall report is being assembled; whatever dict a hook returns merges
# into the report, so the report POINTS AT the artifacts the stall
# triggered (``flight``/``profile`` keys). Hooks must be fast-ish and
# may never raise into the watchdog (guarded below).
_stall_hooks: list = []


def register_stall_hook(fn) -> None:
    """Register ``fn(run_dir, entry) -> dict | None`` to run during
    stall-report assembly (idempotent per function object)."""
    with _lock:
        if fn not in _stall_hooks:
            _stall_hooks.append(fn)


def unregister_stall_hook(fn) -> None:
    with _lock:
        if fn in _stall_hooks:
            _stall_hooks.remove(fn)


def beat(unit: str, query: str | None = None, phase: str | None = None,
         attempt: int | None = None, **info) -> None:
    """Publish one monotonic heartbeat for ``unit``. Keyword context
    (query/phase/attempt) lands in stall reports and liveness
    snapshots; ``count`` increments per beat so watchers can tell
    "same beat re-read" from "no new beat"."""
    now = time.monotonic()
    with _lock:
        prev = _beats.get(unit)
        _beats[unit] = {
            "t": now, "query": query, "phase": phase,
            "attempt": attempt,
            "count": (prev["count"] + 1) if prev else 1, **info,
        }


def clear_unit(unit: str) -> None:
    """Drop a finished unit — its last beat must not age into a
    phantom stall."""
    with _lock:
        _beats.pop(unit, None)


def reset() -> None:
    """Drop every unit (tests)."""
    with _lock:
        _beats.clear()


def snapshot_heartbeats() -> dict:
    """{unit: {age_s, query, phase, attempt, count}} at call time
    ({} when nothing ever beat — the snapshot emitter omits the key)."""
    now = time.monotonic()
    with _lock:
        return {
            unit: {**{k: v for k, v in e.items() if k != "t"},
                   "age_s": round(now - e["t"], 3)}
            for unit, e in _beats.items()
        }


def _freshest() -> tuple[str, dict] | None:
    with _lock:
        if not _beats:
            return None
        unit = max(_beats, key=lambda u: _beats[u]["t"])
        return unit, dict(_beats[unit])


def _thread_stacks() -> dict:
    """{thread name: [frame strings]} for every live thread — the
    post-mortem a hung process cannot write for itself."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = names.get(ident, f"thread-{ident}")
        out[label] = [line.rstrip("\n")
                      for line in traceback.format_stack(frame)]
    return out


def dump_stall_report(run_dir: str, unit: str, entry: dict,
                      stall_s: float, action: str) -> str:
    """Write ``stall-<query>.json`` (all-thread stacks + live metrics +
    the stalled unit's last heartbeat) into ``run_dir``; returns the
    path. Repeat stalls suffix ``-2``, ``-3``... instead of clobbering
    the first report."""
    from nds_tpu.io.integrity import write_json_atomic
    from nds_tpu.obs import metrics as obs_metrics
    label = str(entry.get("query") or unit or "unknown")
    label = "".join(c if (c.isalnum() or c in "-_.") else "_"
                    for c in label)
    doc = {
        "unit": unit,
        "query": entry.get("query"),
        "phase": entry.get("phase"),
        "attempt": entry.get("attempt"),
        "age_s": round(time.monotonic() - entry["t"], 3),
        "stall_s": stall_s,
        "action": action,
        "ts": time.time(),
        "pid": os.getpid(),
        "heartbeats": snapshot_heartbeats(),
        "threads": _thread_stacks(),
        "metrics": obs_metrics.snapshot(),
    }
    # stall hooks: a registered flight recorder dumps its span ring,
    # a registered profiler grabs an on-demand XLA capture — and the
    # report carries pointers to both, so the post-mortem trail starts
    # here instead of in a directory listing
    with _lock:
        hooks = list(_stall_hooks)
    for hook in hooks:
        try:
            extra = hook(run_dir or ".", dict(entry))
        except Exception as exc:  # noqa: BLE001 - never kill the report
            doc.setdefault("hook_errors", []).append(
                f"{type(exc).__name__}: {exc}")
            continue
        if isinstance(extra, dict):
            doc.update(extra)
    os.makedirs(run_dir or ".", exist_ok=True)
    path = os.path.join(run_dir or ".", f"stall-{label}.json")
    n = 1
    while os.path.exists(path):
        n += 1
        path = os.path.join(run_dir or ".", f"stall-{label}-{n}.json")
    write_json_atomic(path, doc)
    return path


class Watchdog:
    """Daemon thread alarming on heartbeat silence.

    ``action``: ``report`` dumps the stall report and keeps watching
    (the interactive default); ``kill`` dumps and then hard-exits with
    EXIT_STALLED — the right behavior for a supervised subprocess
    stream, where the parent restarts a killed child but can do nothing
    for a wedged one."""

    ACTIONS = ("report", "kill")

    def __init__(self, stall_s: float, action: str = "report",
                 run_dir: str = ".", interval_s: float | None = None,
                 _exit=os._exit):
        if stall_s <= 0:
            raise ValueError("stall_s must be > 0")
        if action not in self.ACTIONS:
            raise ValueError(f"unknown watchdog action {action!r} "
                             f"(known: {', '.join(self.ACTIONS)})")
        self.stall_s = stall_s
        self.action = action
        self.run_dir = run_dir
        self.interval_s = interval_s or max(0.2, stall_s / 4.0)
        self.stall_reports: list[str] = []
        self._exit = _exit
        self._reported_at: float | None = None  # beat time last reported
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @classmethod
    def from_config(cls, config, run_dir: str) -> "Watchdog | None":
        """``engine.watchdog.stall_s`` / ``engine.watchdog.action``
        (None when unconfigured)."""
        v = config.get("engine.watchdog.stall_s")
        if v is None or float(v) <= 0:
            return None
        return cls(float(v), config.get("engine.watchdog.action",
                                        "report"), run_dir)

    @classmethod
    def from_env(cls, run_dir: str) -> "Watchdog | None":
        """``NDS_TPU_WATCHDOG=stall_s[:action]`` — how a stream
        supervisor arms its children without threading config files."""
        spec = os.environ.get(WATCHDOG_ENV)
        if not spec:
            return None
        stall, _sep, action = spec.partition(":")
        return cls(float(stall), action or "report", run_dir)

    def check_once(self, now: float | None = None) -> str | None:
        """One alarm evaluation (the thread loop body; tests drive it
        directly). Returns the stall-report path when a stall was just
        reported, else None."""
        newest = _freshest()
        if newest is None:
            return None
        unit, entry = newest
        now = time.monotonic() if now is None else now
        if now - entry["t"] <= self.stall_s:
            return None
        if self._reported_at == entry["t"]:
            return None  # this silence is already on disk; re-arm on beat
        self._reported_at = entry["t"]
        from nds_tpu.obs import metrics as obs_metrics
        obs_metrics.counter("watchdog_stalls_total").inc()
        path = dump_stall_report(self.run_dir, unit, entry,
                                 self.stall_s, self.action)
        self.stall_reports.append(path)
        print(f"[watchdog] no heartbeat for {now - entry['t']:.1f}s "
              f"(unit={unit} query={entry.get('query')} "
              f"phase={entry.get('phase')}) — report: {path}")
        if self.action == "kill":
            sys.stdout.flush()
            sys.stderr.flush()
            self._exit(EXIT_STALLED)
        return path

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception as exc:  # noqa: BLE001 - never kill the run
                print(f"[watchdog] check failed: "
                      f"{type(exc).__name__}: {exc}")

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="nds-tpu-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
