"""Phase journal: crash-resumable whole-benchmark orchestration.

Execution Templates (PAPERS.md) makes the case that long-running cloud
query workloads need cheap recovery from PARTIAL failure — re-running
a finished three-hour load phase because throughput round 2 crashed is
the whole-run-restart anti-pattern. The orchestrator
(``nds/bench.py``) records each completed phase here, with the
timings the composite metric needs, into ``bench_state.json``;
``--resume`` replays completed phases from the journal instead of
re-running them, so a crash costs only the phase it interrupted.

The journal is guarded by a digest of the bench config: resuming
under a DIFFERENT config would splice timings from two different
workloads into one metric, so a mismatch refuses loudly. Writes are
atomic (tmp + rename) — a crash mid-write leaves the previous valid
journal, never a torn one — and the payload is CRC-stamped
(io/integrity.py): a journal torn by forces outside the writer (full
disk, copied mid-write, hand-edited) is DETECTED on ``--resume`` and
degrades to a clean fresh run with a warning, never a crash and never
a silent splice of half-recorded phases.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from nds_tpu.io import integrity


class JournalMismatch(RuntimeError):
    """The on-disk journal belongs to a different bench config."""


def config_digest(cfg: dict) -> str:
    """Stable fingerprint of the bench config (sorted-key JSON)."""
    blob = json.dumps(cfg, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class PhaseJournal:
    """Completed-phase record keyed by phase name.

    ``complete(name, **timings)`` journals a finished phase;
    ``done(name)`` / ``timings(name)`` replay it on resume."""

    VERSION = 1

    def __init__(self, path: str, digest: str | None = None):
        self.path = path
        self.digest = digest
        self.state: dict = {"version": self.VERSION,
                            "config_digest": digest, "phases": {}}

    def load(self) -> bool:
        """Read the journal if present; returns True when prior state
        exists. Raises JournalMismatch when it was written under a
        different config digest. A TORN journal (truncated JSON, CRC
        mismatch) is not prior state: warn and return False so the run
        degrades to a clean fresh start instead of crashing — re-running
        phases is always correct, replaying spliced ones never is."""
        if not os.path.exists(self.path):
            return False
        try:
            with open(self.path) as f:
                state = json.load(f)
            torn = not integrity.check_crc(state)
        except ValueError:
            torn = True
            state = None
        if torn or not isinstance(state, dict):
            print(f"WARNING: journal {self.path} is torn/corrupt — "
                  f"ignoring it and starting fresh")
            return False
        state.pop("crc", None)
        recorded = state.get("config_digest")
        if (self.digest is not None and recorded is not None
                and recorded != self.digest):
            raise JournalMismatch(
                f"{self.path} was written for config {recorded}, "
                f"current config is {self.digest} — refusing to splice "
                f"timings across configs (delete it to start over)")
        self.state = state
        self.state.setdefault("phases", {})
        return bool(self.state["phases"])

    def done(self, name: str) -> bool:
        return name in self.state["phases"]

    def timings(self, name: str) -> dict:
        entry = self.state["phases"].get(name, {})
        return dict(entry.get("timings", {}))

    def complete(self, name: str, **timings) -> None:
        self.state["phases"][name] = {
            "completed_at": time.time(),
            "timings": timings,
        }
        self.write()

    def write(self) -> None:
        # CRC-stamped + atomic: a reader can always tell a complete
        # journal from a torn one (integrity.py contract)
        integrity.write_json_atomic(self.path,
                                    integrity.stamp_crc(self.state))

    def reset(self) -> None:
        """Fresh-run entry: drop any prior state on disk (a non-resume
        run must not leave a stale journal a LATER --resume could
        replay)."""
        self.state = {"version": self.VERSION,
                      "config_digest": self.digest, "phases": {}}
        self.write()
