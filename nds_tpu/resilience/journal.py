"""Phase + query journals: crash-resumable benchmark orchestration.

Execution Templates (PAPERS.md) makes the case that long-running cloud
query workloads need cheap recovery from PARTIAL failure — re-running
a finished three-hour load phase because throughput round 2 crashed is
the whole-run-restart anti-pattern. Two granularities live here:

- :class:`PhaseJournal` — the orchestrator (``nds/bench.py``) records
  each completed phase, with the timings the composite metric needs,
  into ``bench_state.json``; ``--resume`` replays completed phases
  from the journal instead of re-running them, so a crash costs only
  the phase it interrupted.

- :class:`QueryJournal` — the power loop (utils/power_core.py) and the
  in-process throughput streams append EVERY completed statement
  (name, wall ms, status, result digest, incarnation) to a per-phase
  query journal, so ``--resume`` on the power drivers restarts
  MID-PHASE at the next unfinished statement: a preemption at query 87
  of a 99-query power run costs at most the one in-flight query, not
  86 finished ones. Each query also records its execution *starts*
  per incarnation, which is how the soak gate (tools/soak_check.py)
  proves no query ever executed twice.

Both journals are guarded by a digest of the driving config: resuming
under a DIFFERENT config would splice timings from two different
workloads into one metric, so a mismatch refuses loudly. Writes are
atomic (tmp + rename) — a crash mid-write leaves the previous valid
journal, never a torn one — and the payload is CRC-stamped
(io/integrity.py): a journal torn by forces outside the writer (full
disk, copied mid-write, hand-edited) is DETECTED on ``--resume`` and
degrades to a clean fresh run with a warning, never a crash and never
a silent splice of half-recorded state. Every torn-journal degradation
counts on ``journal_resets_total`` and surfaces in the BenchReport
``degradations`` block (utils/report.py) — a silent fresh start cannot
hide inside a long run.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from nds_tpu.analysis import locksan
from nds_tpu.io import integrity


def _count_reset() -> None:
    """A torn/corrupt journal was thrown away: count it so the
    degradation is visible in metrics snapshots, flight dumps and the
    BenchReport ``degradations`` block."""
    from nds_tpu.obs import metrics as obs_metrics
    obs_metrics.counter("journal_resets_total").inc()


class JournalMismatch(RuntimeError):
    """The on-disk journal belongs to a different bench config."""


def config_digest(cfg: dict) -> str:
    """Stable fingerprint of the bench config (sorted-key JSON)."""
    blob = json.dumps(cfg, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class PhaseJournal:
    """Completed-phase record keyed by phase name.

    ``complete(name, **timings)`` journals a finished phase;
    ``done(name)`` / ``timings(name)`` replay it on resume."""

    VERSION = 1

    def __init__(self, path: str, digest: str | None = None):
        self.path = path
        self.digest = digest
        self.state: dict = {"version": self.VERSION,
                            "config_digest": digest, "phases": {}}

    def load(self) -> bool:
        """Read the journal if present; returns True when prior state
        exists. Raises JournalMismatch when it was written under a
        different config digest. A TORN journal (truncated JSON, CRC
        mismatch) is not prior state: warn and return False so the run
        degrades to a clean fresh start instead of crashing — re-running
        phases is always correct, replaying spliced ones never is."""
        if not os.path.exists(self.path):
            return False
        try:
            with open(self.path) as f:
                state = json.load(f)
            torn = not integrity.check_crc(state)
        except ValueError:
            torn = True
            state = None
        if torn or not isinstance(state, dict):
            print(f"WARNING: journal {self.path} is torn/corrupt — "
                  f"ignoring it and starting fresh")
            _count_reset()
            return False
        state.pop("crc", None)
        recorded = state.get("config_digest")
        if (self.digest is not None and recorded is not None
                and recorded != self.digest):
            raise JournalMismatch(
                f"{self.path} was written for config {recorded}, "
                f"current config is {self.digest} — refusing to splice "
                f"timings across configs (delete it to start over)")
        self.state = state
        self.state.setdefault("phases", {})
        return bool(self.state["phases"])

    def done(self, name: str) -> bool:
        return name in self.state["phases"]

    def timings(self, name: str) -> dict:
        entry = self.state["phases"].get(name, {})
        return dict(entry.get("timings", {}))

    def complete(self, name: str, **timings) -> None:
        self.state["phases"][name] = {
            "completed_at": time.time(),
            "timings": timings,
        }
        self.write()

    def write(self) -> None:
        # CRC-stamped + atomic: a reader can always tell a complete
        # journal from a torn one (integrity.py contract)
        integrity.write_json_atomic(self.path,
                                    integrity.stamp_crc(self.state))

    def reset(self) -> None:
        """Fresh-run entry: drop any prior state on disk (a non-resume
        run must not leave a stale journal a LATER --resume could
        replay)."""
        self.state = {"version": self.VERSION,
                      "config_digest": self.digest, "phases": {}}
        self.write()


class QueryJournal:
    """Per-phase, query-granular resume journal.

    One file per phase (``<phase>_queries.json`` in the run dir), one
    entry per statement. ``start(name)`` marks an execution attempt
    (appending the current incarnation to the query's ``starts`` list
    BEFORE dispatch — a process killed mid-query leaves a start with no
    completion, which is exactly the at-most-one-lost-query evidence);
    ``record(name, ...)`` marks completion with the wall clock, final
    status and result digest the merged phase report needs. A resumed
    incarnation (``begin_incarnation``) replays ``done`` queries and
    re-runs only unfinished ones. Thread-safe: the drain deadline
    thread (resilience/drain.py) may stamp an abort while the main
    thread is wedged inside a query."""

    VERSION = 1

    def __init__(self, path: str, phase: str = "",
                 digest: str | None = None):
        self.path = path
        self.phase = phase
        self.digest = digest
        # rank-0-writes (the BenchReport rule): non-primary SPMD ranks
        # track state in memory (their replay decisions must match the
        # primary's) but never race it onto the shared file
        self.readonly = False
        self._lock = locksan.lock("resilience.QueryJournal._lock")
        self.state: dict = self._fresh()

    def _fresh(self) -> dict:
        return {"version": self.VERSION, "phase": self.phase,
                "config_digest": self.digest, "incarnation": 0,
                "queries": {}}

    def _incarnation_locked(self) -> int:
        return int(self.state.get("incarnation", 0))

    @property
    def incarnation(self) -> int:
        with self._lock:
            return self._incarnation_locked()

    def load(self) -> bool:
        """Read prior state; same contract as PhaseJournal.load — a
        torn journal warns, counts ``journal_resets_total`` and returns
        False (degrade to a fresh run; re-running statements is always
        correct, splicing half-recorded ones never is); a journal from
        a DIFFERENT config refuses loudly."""
        if not os.path.exists(self.path):
            return False
        try:
            with open(self.path) as f:
                state = json.load(f)
            torn = not integrity.check_crc(state)
        except ValueError:
            torn = True
            state = None
        if torn or not isinstance(state, dict) \
                or not isinstance(state.get("queries"), dict):
            print(f"WARNING: query journal {self.path} is torn/corrupt "
                  f"— ignoring it and starting fresh")
            _count_reset()
            return False
        state.pop("crc", None)
        recorded = state.get("config_digest")
        if (self.digest is not None and recorded is not None
                and recorded != self.digest):
            raise JournalMismatch(
                f"{self.path} was written for config {recorded}, "
                f"current config is {self.digest} — refusing to resume "
                f"a different workload (delete it to start over)")
        with self._lock:
            self.state = state
            self.state.setdefault("queries", {})
            self.state.setdefault("incarnation", 0)
        return bool(state["queries"])

    def begin_incarnation(self) -> int:
        """A resumed process bumps the incarnation counter; every start
        and completion it records carries the new number, so the merged
        phase report and the soak gate can attribute each execution."""
        with self._lock:
            inc = self._incarnation_locked() + 1
            self.state["incarnation"] = inc
        self.write()
        return inc

    # ------------------------------------------------------- recording

    def start(self, name: str) -> None:
        """Mark an execution attempt BEFORE dispatch (atomic write: a
        kill -9 one instruction later still leaves the start on
        disk)."""
        with self._lock:
            q = self.state["queries"].setdefault(name, {"starts": []})
            q.setdefault("starts", []).append(
                self._incarnation_locked())
        self.write()

    def record(self, name: str, wall_ms: float, status: str,
               result_digest: str | None = None) -> None:
        """Journal a finished statement (Completed OR Failed — a failed
        query is a FINAL state in the power-run contract; resume must
        not re-run it and change the metric)."""
        with self._lock:
            q = self.state["queries"].setdefault(name, {"starts": []})
            q.pop("aborted", None)
            q.update({"done": True, "wall_ms": round(float(wall_ms), 3),
                      "status": str(status),
                      "incarnation": self._incarnation_locked(),
                      "ts": time.time()})
            if result_digest:
                q["result_digest"] = result_digest
        self.write()

    def mark_aborted(self, name: str | None,
                     reason: str = "drain-deadline") -> None:
        """The drain deadline expired with this query in flight: stamp
        it explicitly not-done so a post-mortem can tell a deliberate
        abort from a crash. Safe from any thread; no-op without a
        query."""
        if not name:
            return
        with self._lock:
            q = self.state["queries"].setdefault(name, {"starts": []})
            if q.get("done"):
                return  # finished after all: completion wins
            q["aborted"] = reason
        self.write()

    # --------------------------------------------------------- readout

    def done(self, name: str) -> bool:
        # readouts take the lock too (the PR-10 review finding this
        # module's auditor rule NDSR201 now codifies): the drain
        # deadline thread mutates ``state`` while the main loop reads
        # its replay decisions
        with self._lock:
            return bool(self.state["queries"].get(name, {}).get("done"))

    def entry(self, name: str) -> dict:
        with self._lock:
            return dict(self.state["queries"].get(name, {}))

    def completed(self) -> dict:
        """{name: entry} of every journaled-done statement."""
        with self._lock:
            return {n: dict(e)
                    for n, e in self.state["queries"].items()
                    if e.get("done")}

    def starts(self, name: str) -> list:
        with self._lock:
            return list(self.state["queries"].get(name,
                                                  {}).get("starts", []))

    def write(self) -> None:
        if self.readonly:
            return
        with self._lock:
            doc = integrity.stamp_crc(
                json.loads(json.dumps(self.state, default=str)))
            # the file write stays INSIDE the lock: the serialized doc
            # and the rename order must agree — a later snapshot must
            # never be replaced by an earlier one racing it to the
            # rename (write_json_atomic's tmp names are thread-unique,
            # so only the ORDER needs the lock, but it does need it)
            integrity.write_json_atomic(self.path, doc)

    def reset(self) -> None:
        """Fresh-run entry: drop prior state on disk (same contract as
        PhaseJournal.reset)."""
        with self._lock:
            self.state = self._fresh()
        self.write()
