"""Fault-injection & resilience layer.

The reference harness's only failure story is "keep going and downgrade
the exit code" (``--allow_failure``); a production-scale run needs more:
transient device OOMs, exchange overflows and mid-run crashes are
routine events to recover from, not reasons to restart a multi-hour
benchmark. This package is the shared vocabulary for that recovery:

- ``faults``    seeded, deterministic fault injection at named sites
                (``NDS_TPU_FAULTS`` schedule; zero-cost no-op when
                unset; ``hang``/``corrupt`` kinds make the watchdog
                and integrity paths testable)
- ``retry``     transient-vs-deterministic failure classification plus
                ``RetryPolicy`` (exponential backoff, jitter, attempt
                caps, per-query wall-clock deadlines enforced between
                attempts AND at chunk boundaries inside them)
- ``journal``   phase journal for resumable whole-benchmark runs
                (``bench_state.json`` + ``--resume``; CRC-stamped, a
                torn journal degrades to a fresh run)
- ``watchdog``  process-local heartbeat registry + hang watchdog
                (stall reports with all-thread stacks,
                ``engine.watchdog.*`` / ``NDS_TPU_WATCHDOG``)
- ``supervise`` subprocess stream fleets: heartbeat liveness, kill on
                stall, restart-once from the last completed query

See README "Resilience" for the schedule syntax and config keys.
"""

from nds_tpu.resilience.faults import fault_point  # noqa: F401
from nds_tpu.resilience.retry import RetryPolicy, RetryStats, classify  # noqa: F401
