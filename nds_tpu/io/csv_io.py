"""Raw '|'-delimited text IO (dbgen/dsdgen .dat format) + Parquet.

The raw-data contract matches what the TPC tools emit and the reference
consumes (`nds/nds_transcode.py:56-66` reads '|'-CSV with an explicit
schema; `nds-h/nds_h_schema.py:50-61` adds an 'ignore' trailing column for
dbgen's trailing '|'). Here ``trailing_delimiter=True`` handles that in the
reader. Parquet read/write goes through pyarrow; string columns round-trip
as Arrow dictionary arrays so the sorted-code invariant is rebuilt on read.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq

from nds_tpu.engine.types import (
    DateType, DecimalType, FloatType, IntType, Schema, StringType,
)
from nds_tpu.io.host_table import HostColumn, HostTable, encode_strings

_EPOCH = np.datetime64("1970-01-01", "D")


def _arrow_read_type(dtype) -> pa.DataType:
    if isinstance(dtype, IntType):
        return pa.int64() if dtype.bits == 64 else pa.int32()
    if isinstance(dtype, FloatType):
        return pa.float64() if dtype.bits == 64 else pa.float32()
    if isinstance(dtype, DecimalType):
        return pa.decimal128(max(dtype.precision, 18), dtype.scale)
    if isinstance(dtype, DateType):
        return pa.date32()
    if isinstance(dtype, StringType):
        return pa.string()
    raise TypeError(f"unsupported dtype {dtype}")


def read_tbl(paths: list[str] | str, name: str, schema: Schema,
             trailing_delimiter: bool = True) -> HostTable:
    """Read one table from one or more '|'-delimited files."""
    from nds_tpu.io import integrity
    from nds_tpu.resilience import faults
    if isinstance(paths, str):
        paths = [paths]
    faults.fault_point("io.read", table=name, paths=paths)
    # digest verification (io.verify_digests / NDS_TPU_VERIFY_DIGESTS):
    # a flipped bit in a raw chunk fails HERE with CorruptArtifact —
    # deterministic, never retried — instead of loading wrong rows
    integrity.verify_paths(paths, name)
    names = schema.names + (["_trailing"] if trailing_delimiter else [])
    types = {f.name: _arrow_read_type(f.dtype) for f in schema}
    if trailing_delimiter:
        types["_trailing"] = pa.string()
    from nds_tpu.resilience import watchdog
    tables = []
    for p in paths:
        # per-chunk heartbeat: multi-chunk fact reads on a loaded box
        # must not look like a hang to the watchdog
        watchdog.beat("engine", phase="io.read", table=name)
        if os.path.getsize(p) == 0:
            continue  # zero-row chunks are legitimate (fixed tables)
        t = pacsv.read_csv(
            p,
            read_options=pacsv.ReadOptions(column_names=names),
            parse_options=pacsv.ParseOptions(delimiter="|"),
            convert_options=pacsv.ConvertOptions(column_types=types),
        )
        if trailing_delimiter:
            t = t.drop(["_trailing"])
        tables.append(t)
    if not tables:
        empty = pa.table(
            {f.name: pa.array([], type=_arrow_read_type(f.dtype)) for f in schema})
        return from_arrow(name, schema, empty)
    return from_arrow(name, schema, pa.concat_tables(tables))


def from_arrow(name: str, schema: Schema, t: pa.Table) -> HostTable:
    """Arrow table -> HostTable, carrying arrow validity bitmaps over as
    engine null masks (True = valid). Null slots are filled with 0/"" in
    the value arrays so downstream numpy code never sees NaN."""
    cols: dict[str, HostColumn] = {}
    for f in schema:
        arr = t.column(f.name).combine_chunks()
        mask = None
        if arr.null_count:
            mask = arr.is_valid().to_numpy(zero_copy_only=False)
        if isinstance(f.dtype, StringType):
            # arrow-native dictionary encode, then remap codes so the
            # dictionary is sorted (code order == lexicographic order);
            # only the (small) dictionary is ever sorted, not the column
            if not pa.types.is_dictionary(arr.type):
                arr = arr.dictionary_encode()
            raw_dict = np.asarray(arr.dictionary.to_pylist(), dtype=object)
            raw_codes = arr.indices.fill_null(0).to_numpy(
                zero_copy_only=False).astype(np.int32)
            order = np.argsort(raw_dict.astype(str), kind="stable")
            remap = np.empty(len(raw_dict), dtype=np.int32)
            remap[order] = np.arange(len(raw_dict), dtype=np.int32)
            codes = remap[raw_codes] if len(raw_dict) else raw_codes
            cols[f.name] = HostColumn(f.dtype, codes, raw_dict[order], mask)
        elif isinstance(f.dtype, DecimalType):
            s = f.dtype.scale
            if f.dtype.precision <= 15:
                # float64 is exact for <= 15 significant digits: vectorized
                as_f = arr.cast(pa.float64()).to_numpy(zero_copy_only=False)
                ints = np.round(np.nan_to_num(as_f) * 10**s).astype(np.int64)
            else:
                ints = np.array(
                    [0 if v is None else int(v.scaleb(s)) for v in arr.to_pylist()],
                    dtype=np.int64)
            cols[f.name] = HostColumn(f.dtype, ints, None, mask)
        elif isinstance(f.dtype, DateType):
            d = arr.cast(pa.int32()).fill_null(0)
            cols[f.name] = HostColumn(
                f.dtype, d.to_numpy(zero_copy_only=False), None, mask)
        elif isinstance(f.dtype, (IntType, FloatType)):
            cols[f.name] = HostColumn(
                f.dtype, arr.fill_null(0).to_numpy(zero_copy_only=False),
                None, mask)
        else:
            cols[f.name] = HostColumn(
                f.dtype, arr.to_numpy(zero_copy_only=False), None, mask)
    return HostTable(name, schema, cols)


def to_arrow(table: HostTable) -> pa.Table:
    arrays, names = [], []
    for f in table.schema:
        col = table.columns[f.name]
        names.append(f.name)
        # arrow mask convention: True = NULL (inverse of the engine's)
        amask = None if col.null_mask is None else ~col.null_mask
        if col.is_string:
            dict_arr = pa.DictionaryArray.from_arrays(
                pa.array(col.values, type=pa.int32(), mask=amask),
                pa.array(list(col.dictionary), type=pa.string()))
            arrays.append(dict_arr)
        elif isinstance(f.dtype, DecimalType):
            s = f.dtype.scale
            target = pa.decimal128(max(f.dtype.precision, 18), s)
            if f.dtype.precision <= 15:
                # exact: |value| < 10^15 so float64 round-trips the cents
                as_f = col.values.astype(np.float64) / 10**s
                arrays.append(
                    pa.array(as_f, mask=amask).cast(target, safe=False))
            else:
                from decimal import Decimal
                vals = [Decimal(int(v)).scaleb(-s) for v in col.values]
                if amask is not None:
                    vals = [None if m else v
                            for v, m in zip(vals, amask)]
                arrays.append(pa.array(vals, type=target))
        elif isinstance(f.dtype, DateType):
            arrays.append(pa.array(col.values, type=pa.int32(),
                                   mask=amask).cast(pa.date32()))
        else:
            arrays.append(pa.array(col.values, mask=amask))
    return pa.Table.from_arrays(arrays, names=names)


def write_parquet(table: HostTable, path: str, compression: str = "snappy",
                  row_group_rows: int = 1 << 20) -> None:
    write_arrow(to_arrow(table), path, "parquet", compression,
                row_group_rows)


def read_parquet(paths: list[str] | str, name: str, schema: Schema) -> HostTable:
    if isinstance(paths, str):
        paths = [paths]
    # ParquetFile, not pq.read_table: read_table wraps single files in a
    # dataset and INFERS hive partitioning from `col=value` path
    # segments (pyarrow >= 13). The transcode layout nests files under
    # `<table>/<part_col>=<band>/part-N.parquet` WITH the partition
    # column physically present in every file, so the inferred
    # dictionary<int32> partition field collides with the physical
    # int32 column and the schema merge fails (ArrowTypeError). Reading
    # the file directly skips path inference entirely — partition
    # columns come from the file bytes, which the writer guarantees.
    tables = [pq.ParquetFile(p).read() for p in paths]
    return from_arrow(name, schema, pa.concat_tables(tables, promote_options="permissive"))


# warehouse output formats beyond parquet (the reference's transcode
# writes parquet/orc/avro/json, `nds/nds_transcode.py:69-152`; avro via
# the built-in spec container codec in io/avro_io.py)
FORMAT_EXT = {"parquet": ".parquet", "orc": ".orc", "json": ".json",
              "avro": ".avro"}


def write_arrow(t: pa.Table, path: str, fmt: str = "parquet",
                compression: str = "snappy",
                row_group_rows: int = 1 << 20) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if fmt == "parquet":
        pq.write_table(t, path, compression=compression,
                       row_group_size=row_group_rows)
    elif fmt == "orc":
        import pyarrow.orc as paorc
        cols = []
        for i, f in enumerate(t.schema):
            c = t.column(i)
            if pa.types.is_dictionary(f.type):
                c = c.cast(pa.string())
            cols.append(c)
        paorc.write_table(pa.Table.from_arrays(cols,
                                               names=t.column_names),
                          path, compression=compression)
    elif fmt == "json":
        # JSON-lines records, the layout pyarrow.json reads back; dates
        # as ISO strings, decimals as exact decimal strings
        import json as _json
        with open(path, "w") as f:
            for row in t.to_pylist():
                f.write(_json.dumps(row, default=str) + "\n")
    elif fmt == "avro":
        raise ValueError(
            "avro writes go through write_table (HostTable input); "
            "an arrow Table has no engine schema to map from")
    else:
        raise ValueError(f"unknown output format {fmt!r}")


def write_table(table: HostTable, path: str, fmt: str = "parquet",
                compression: str = "snappy") -> None:
    if fmt == "avro":
        from nds_tpu.io import avro_io
        if compression in (None, "none"):
            codec = "null"
        elif compression == "deflate":
            codec = "deflate"
        elif compression == "snappy":
            # the CLI-wide default targets parquet; no snappy codec in
            # this image, so substitute deflate AUDIBLY, never silently
            from nds_tpu.utils.report import TaskFailureCollector
            TaskFailureCollector.notify(
                "avro: no snappy codec in this environment, writing "
                "deflate instead")
            codec = "deflate"
        else:
            raise ValueError(
                f"unsupported avro compression {compression!r} "
                f"(available: none, deflate)")
        avro_io.write_avro(table, path, table.schema, codec=codec)
        return
    write_arrow(to_arrow(table), path, fmt, compression)


def read_paths_auto(paths: list[str], name: str, schema: Schema,
                    default_fmt: str) -> HostTable:
    """Read warehouse files whose formats may differ per file: snapshot
    manifests mix the load-time warehouse format with the parquet
    version files maintenance commits (io/snapshots.py). Buckets by
    extension, reads each bucket in its own format, and rebuilds one
    table (string dictionaries re-encode across buckets)."""
    ext_to_fmt = {ext: f for f, ext in FORMAT_EXT.items()}
    groups: dict[str, list[str]] = {}
    for p in paths:
        ext = os.path.splitext(p)[1]
        groups.setdefault(ext_to_fmt.get(ext, default_fmt),
                          []).append(p)
    if len(groups) == 1:
        fmt, ps = next(iter(groups.items()))
        return read_table_fmt(ps, name, schema, fmt)
    parts = [read_table_fmt(ps, name, schema, fmt)
             for fmt, ps in groups.items()]
    arrays: dict[str, np.ndarray] = {}
    for f in schema:
        cols = [t.columns[f.name] for t in parts]
        vals = np.concatenate([c.decode() if c.is_string else c.values
                               for c in cols])
        arrays[f.name] = vals
        if f.nullable:
            arrays[f.name + "#null"] = np.concatenate(
                [c.null_mask if c.null_mask is not None
                 else np.ones(len(c.values), dtype=bool) for c in cols])
    from nds_tpu.io.host_table import from_arrays
    return from_arrays(name, schema, arrays)


def read_table_fmt(paths: list[str] | str, name: str, schema: Schema,
                   fmt: str) -> HostTable:
    """Read a warehouse table written by ``write_table`` in any format.

    When digest verification is on (io/integrity.py), every file is
    re-hashed against its table's ``_manifest.json`` before parsing:
    corruption surfaces as a fail-fast CorruptArtifact naming the file
    and both digests, never as silently wrong query output."""
    from nds_tpu.io import integrity
    from nds_tpu.resilience import faults
    if isinstance(paths, str):
        paths = [paths]
    faults.fault_point("io.read", table=name, fmt=fmt, paths=paths)
    integrity.verify_paths(paths, name)
    if fmt == "parquet":
        return read_parquet(paths, name, schema)
    if fmt == "avro":
        from nds_tpu.io import avro_io
        return avro_io.read_avro(paths, name, schema)
    if isinstance(paths, str):
        paths = [paths]
    if fmt == "orc":
        import pyarrow.orc as paorc
        tables = [paorc.read_table(p) for p in paths]
        return from_arrow(name, schema,
                          pa.concat_tables(tables,
                                           promote_options="permissive"))
    if fmt == "json":
        import pyarrow.json as pajson
        # dates and decimals are ISO/decimal STRINGS in the json lines
        # (json has no such types); read as string, cast after
        read_types, casts = {}, {}
        for f in schema:
            t = _arrow_read_type(f.dtype)
            if isinstance(f.dtype, (DateType, DecimalType)):
                read_types[f.name] = pa.string()
                casts[f.name] = t
            else:
                read_types[f.name] = t
        want = pa.schema(read_types)
        tables = []
        for p in paths:
            t = pajson.read_json(
                p, parse_options=pajson.ParseOptions(
                    explicit_schema=want))
            cols = []
            for i, fld in enumerate(t.schema):
                c = t.column(i)
                if fld.name in casts:
                    c = c.cast(casts[fld.name])
                cols.append(c)
            tables.append(pa.Table.from_arrays(
                cols, names=t.column_names))
        return from_arrow(name, schema,
                          pa.concat_tables(tables,
                                           promote_options="permissive"))
    raise ValueError(f"unknown input format {fmt!r}")


def write_tbl(arrays: dict[str, np.ndarray], schema: Schema, path: str,
              trailing_delimiter: bool = True) -> None:
    """Write generator output in dbgen's .tbl text format (for parity with
    the reference raw-data layout, `nds-h/nds_h_gen_data.py:109-115`)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    n = len(next(iter(arrays.values())))
    cols = []
    for f in schema:
        arr = arrays[f.name]
        if isinstance(f.dtype, DecimalType):
            s = f.dtype.scale
            ints = arr.astype(np.int64)
            sign = np.where(ints < 0, "-", "")
            mag = np.abs(ints)
            vals = [f"{sign[i]}{mag[i] // 10**s}.{mag[i] % 10**s:0{s}d}"
                    for i in range(n)]
        elif isinstance(f.dtype, DateType):
            vals = [str(_EPOCH + int(v)) for v in arr]
        else:
            vals = [str(v) for v in arr]
        valid = arrays.get(f.name + "#null")
        if valid is not None:
            # dsdgen's NULL convention: an empty field
            vals = [v if ok else "" for v, ok in zip(vals, valid)]
        cols.append(vals)
    end = "|\n" if trailing_delimiter else "\n"
    with open(path, "w") as f:
        for row in zip(*cols):
            f.write("|".join(row) + end)
