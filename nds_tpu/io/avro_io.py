"""Avro Object Container File writer/reader (pure Python, stdlib only).

The reference's transcode writes avro warehouses via spark-avro
(`nds/nds_transcode.py:69-152` with --output_format avro); this image
ships no avro package, so the container format (Apache Avro spec 1.11.1,
"Object Container Files") is implemented directly: magic `Obj\\x01`,
metadata map carrying the JSON schema and codec, 16-byte sync marker,
then length-prefixed record blocks. Codecs: `null` and `deflate`
(zlib, spec's raw-DEFLATE framing) — both readable by any standard
avro implementation.

Type mapping (engine logical types -> avro):
  int8/16/32 -> int        int64 -> long       float32/64 -> float/double
  bool       -> boolean    string -> string
  date       -> int + logicalType:date              (epoch days, as stored)
  decimal(p,s) -> bytes + logicalType:decimal       (big-endian two's
                  complement of the scaled integer, the spec encoding)
Nullable columns are `["null", T]` unions, matching how spark-avro
writes nullable StructFields.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib

import numpy as np

from nds_tpu.engine.types import (
    BoolType, DateType, DecimalType, DType, FloatType, IntType, Schema,
    StringType,
)
from nds_tpu.io.host_table import HostTable, from_arrays

MAGIC = b"Obj\x01"
SYNC = bytes(range(16))  # deterministic marker: files diff stably


# ------------------------------------------------------------ encoding

def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _write_long(buf: io.BytesIO, n: int) -> None:
    n = _zigzag(int(n))
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes((b | 0x80,)))
        else:
            buf.write(bytes((b,)))
            return


def _read_long(buf) -> int:
    shift, acc = 0, 0
    while True:
        b = buf.read(1)[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzigzag(acc)
        shift += 7


def _write_bytes(buf: io.BytesIO, b: bytes) -> None:
    _write_long(buf, len(b))
    buf.write(b)


def _read_bytes(buf) -> bytes:
    return buf.read(_read_long(buf))


def _decimal_bytes(v: int) -> bytes:
    """Big-endian two's complement, minimal length (spec decimal)."""
    v = int(v)
    length = max(1, ((v if v >= 0 else ~v).bit_length() + 8) // 8)
    return v.to_bytes(length, "big", signed=True)


def _long_bytes(n: int) -> bytes:
    """Zigzag varint as bytes (the hot writer path)."""
    n = _zigzag(int(n))
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


_LONG0 = _long_bytes(0)
_LONG1 = _long_bytes(1)

# field kind codes for the per-value loops
_K_LONG, _K_DECIMAL, _K_STRING, _K_FLOAT, _K_BOOL = range(5)


def _kind_of(dt: DType) -> int:
    if isinstance(dt, (IntType, DateType)):
        return _K_LONG
    if isinstance(dt, DecimalType):
        return _K_DECIMAL
    if isinstance(dt, StringType):
        return _K_STRING
    if isinstance(dt, FloatType):
        return _K_FLOAT
    if isinstance(dt, BoolType):
        return _K_BOOL
    raise ValueError(f"no avro mapping for {dt!r}")


# ------------------------------------------------------------- schema

def _avro_type(dt: DType) -> object:
    if isinstance(dt, IntType):
        return "long" if dt.bits == 64 else "int"
    if isinstance(dt, FloatType):
        return "double" if dt.bits == 64 else "float"
    if isinstance(dt, BoolType):
        return "boolean"
    if isinstance(dt, StringType):
        return "string"
    if isinstance(dt, DateType):
        return {"type": "int", "logicalType": "date"}
    if isinstance(dt, DecimalType):
        return {"type": "bytes", "logicalType": "decimal",
                "precision": dt.precision, "scale": dt.scale}
    raise ValueError(f"no avro mapping for {dt!r}")


def avro_schema(name: str, schema: Schema) -> dict:
    fields = []
    for f in schema:
        t = _avro_type(f.dtype)
        fields.append({"name": f.name,
                       "type": ["null", t] if f.nullable else t})
    return {"type": "record", "name": name, "fields": fields}


# ------------------------------------------------------------- writer

def write_avro(table: HostTable, path: str, schema: Schema,
               codec: str = "null", block_rows: int = 65536) -> None:
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    sch = avro_schema(table.name, schema)
    cols = []
    for f in schema:
        c = table.columns[f.name]
        vals = c.decode() if c.is_string else c.values
        # plain Python lists: per-element numpy scalar boxing dominates
        # the row loop otherwise (avro is row-major, so a columnar
        # vectorization would still interleave per record)
        cols.append((f, vals.tolist() if hasattr(vals, "tolist")
                     else list(vals),
                     None if c.null_mask is None
                     else c.null_mask.tolist()))
    n = table.nrows
    with open(path, "wb") as out:
        out.write(MAGIC)
        header = io.BytesIO()
        _write_long(header, 2)  # metadata map: one block of 2 entries
        _write_bytes(header, b"avro.schema")
        _write_bytes(header, json.dumps(sch).encode())
        _write_bytes(header, b"avro.codec")
        _write_bytes(header, codec.encode())
        _write_long(header, 0)  # end of map
        out.write(header.getvalue())
        out.write(SYNC)
        # per-field integer kind codes keep isinstance dispatch out of
        # the per-value loop (avro is row-major, so values interleave
        # per record and a columnar vectorization can't apply)
        plan = []
        for f, vals, mask in cols:
            plan.append((_kind_of(f.dtype), f.nullable, vals, mask,
                         "<d" if (isinstance(f.dtype, FloatType)
                                  and f.dtype.bits == 64) else "<f"))
        for start in range(0, max(n, 1), block_rows):
            stop = min(start + block_rows, n)
            if stop <= start:
                break
            parts: list[bytes] = []
            add = parts.append
            for i in range(start, stop):
                for kind, nullable, vals, mask, ffmt in plan:
                    null = mask is not None and not mask[i]
                    if nullable:
                        add(_LONG1 if not null else _LONG0)
                        if null:
                            continue
                    v = vals[i]
                    if kind == _K_LONG:
                        add(_long_bytes(v))
                    elif kind == _K_DECIMAL:
                        b = _decimal_bytes(v)
                        add(_long_bytes(len(b)))
                        add(b)
                    elif kind == _K_STRING:
                        b = str(v).encode()
                        add(_long_bytes(len(b)))
                        add(b)
                    elif kind == _K_FLOAT:
                        add(struct.pack(ffmt, float(v)))
                    else:  # _K_BOOL
                        add(b"\x01" if v else b"\x00")
            data = b"".join(parts)
            if codec == "deflate":
                # spec: raw DEFLATE — strip the 2-byte zlib header and
                # 4-byte adler32 trailer
                data = zlib.compress(data)[2:-4]
            head = io.BytesIO()
            _write_long(head, stop - start)
            _write_long(head, len(data))
            out.write(head.getvalue())
            out.write(data)
            out.write(SYNC)


# ------------------------------------------------------------- reader

def read_avro(paths: list[str] | str, name: str,
              schema: Schema) -> HostTable:
    if isinstance(paths, str):
        paths = [paths]
    cols: dict[str, list] = {f.name: [] for f in schema}
    nulls: dict[str, list] = {f.name: [] for f in schema}
    for p in paths:
        _read_one(p, schema, cols, nulls)
    arrays: dict[str, np.ndarray] = {}
    for f in schema:
        vals = cols[f.name]
        if isinstance(f.dtype, StringType):
            arrays[f.name] = np.array(
                [v if v is not None else "" for v in vals], dtype=object)
        elif isinstance(f.dtype, FloatType):
            arrays[f.name] = np.array(
                [v if v is not None else 0.0 for v in vals],
                dtype=np.float64 if f.dtype.bits == 64 else np.float32)
        else:
            dt = (np.int64 if (isinstance(f.dtype, IntType)
                               and f.dtype.bits == 64)
                  or isinstance(f.dtype, DecimalType) else np.int32)
            if isinstance(f.dtype, BoolType):
                dt = np.bool_
            arrays[f.name] = np.array(
                [v if v is not None else 0 for v in vals], dtype=dt)
        if f.nullable:
            arrays[f.name + "#null"] = np.array(nulls[f.name],
                                                dtype=bool)
    return from_arrays(name, schema, arrays)


def _read_one(path: str, schema: Schema, cols, nulls) -> None:
    with open(path, "rb") as f:
        raw = f.read()
    buf = io.BytesIO(raw)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path}: not an avro container file")
    meta = {}
    while True:
        count = _read_long(buf)
        if count == 0:
            break
        if count < 0:  # spec: negative count is followed by byte size
            _read_long(buf)
            count = -count
        for _ in range(count):
            k = _read_bytes(buf)
            meta[k.decode()] = _read_bytes(buf)
    codec = meta.get("avro.codec", b"null").decode()
    file_schema = json.loads(meta["avro.schema"])
    order = [fl["name"] for fl in file_schema["fields"]]
    by_name = {f.name: f for f in schema}
    if set(order) != set(by_name):
        raise ValueError(
            f"{path}: avro fields {order} do not match schema")
    plan = []
    for fname in order:
        fld = by_name[fname]
        is64 = isinstance(fld.dtype, FloatType) and fld.dtype.bits == 64
        plan.append((_kind_of(fld.dtype), fld.nullable, cols[fname],
                     nulls[fname], 8 if is64 else 4,
                     "<d" if is64 else "<f"))
    sync = buf.read(16)
    while buf.tell() < len(raw):
        nrec = _read_long(buf)
        size = _read_long(buf)
        data = buf.read(size)
        if codec == "deflate":
            data = zlib.decompress(data, wbits=-15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        if buf.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch")
        _decode_block(data, nrec, plan, cols, nulls)


def _decode_block(data: bytes, nrec: int, plan, cols, nulls) -> None:
    """Index-based block decode: no BytesIO.read(1)-per-byte, no
    isinstance per value (the reader hot path — fact tables are tens of
    millions of values)."""
    pos = 0
    unz = _unzigzag
    for _ in range(nrec):
        for kind, nullable, cvals, cnulls, fsize, ffmt in plan:
            if nullable:
                present = data[pos] == 2  # zigzag(1) = 2, single byte
                pos += 1
                cnulls.append(present)
                if not present:
                    cvals.append(None)
                    continue
            if kind == _K_LONG:
                shift = acc = 0
                while True:
                    b = data[pos]
                    pos += 1
                    acc |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
                cvals.append(unz(acc))
            elif kind in (_K_DECIMAL, _K_STRING):
                shift = acc = 0
                while True:
                    b = data[pos]
                    pos += 1
                    acc |= (b & 0x7F) << shift
                    if not b & 0x80:
                        break
                    shift += 7
                ln = unz(acc)
                raw = data[pos:pos + ln]
                pos += ln
                cvals.append(int.from_bytes(raw, "big", signed=True)
                             if kind == _K_DECIMAL else raw.decode())
            elif kind == _K_FLOAT:
                cvals.append(struct.unpack_from(ffmt, data, pos)[0])
                pos += fsize
            else:  # _K_BOOL
                cvals.append(data[pos] == 1)
                pos += 1
