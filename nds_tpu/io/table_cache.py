"""Disk persistence for HostTables (one .npz per table).

Lets drivers generate a scale factor once and reuse it across runs —
the reference's datagen-then-transcode lifecycle persists data on HDFS
(`nds/nds_gen_data.py:130-180`); here the warehouse is local columnar
files. Used by bench.py so the round benchmark never regenerates data
it already has.
"""

from __future__ import annotations

import os

import numpy as np

from nds_tpu.engine.types import Schema
from nds_tpu.io.host_table import HostColumn, HostTable


def save_table(dirpath: str, table: HostTable) -> str:
    os.makedirs(dirpath, exist_ok=True)
    payload: dict[str, np.ndarray] = {}
    for name, col in table.columns.items():
        payload[f"{name}::values"] = col.values
        if col.dictionary is not None:
            payload[f"{name}::dict"] = col.dictionary.astype(str)
        if col.null_mask is not None:
            payload[f"{name}::mask"] = col.null_mask
    path = os.path.join(dirpath, f"{table.name}.npz")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **payload)
    os.replace(tmp, path)
    # digest into the cache dir's manifest so reuse across runs detects
    # on-disk rot (io/integrity.py; verification gated on load)
    from nds_tpu.io import integrity
    integrity.update_manifest(dirpath, [f"{table.name}.npz"])
    # columnar encoding metadata rides the same manifest (nds_tpu/
    # columnar/): the load-time encoding choice round-trips with the
    # artifact instead of being re-derived on every process start
    from nds_tpu import columnar
    if columnar.enabled():
        columnar.manifest_set_encodings(
            dirpath, table.name,
            columnar.table_specs(table))
        integrity.clear_cache()  # the manifest just changed on disk
    return path


def load_table(dirpath: str, name: str, schema: Schema) -> HostTable | None:
    path = os.path.join(dirpath, f"{name}.npz")
    if not os.path.exists(path):
        return None
    from nds_tpu.io import integrity
    integrity.verify_paths([path], name)
    data = np.load(path, allow_pickle=False)
    cols: dict[str, HostColumn] = {}
    for f in schema:
        key = f"{f.name}::values"
        if key not in data:
            return None  # stale cache with a different schema
        dictionary = None
        if f"{f.name}::dict" in data:
            dictionary = data[f"{f.name}::dict"].astype(object)
        mask = data.get(f"{f.name}::mask")
        cols[f.name] = HostColumn(f.dtype, data[key], dictionary, mask)
    # restore persisted encoding choices (written by save_table under
    # an active columnar mode): seeds the per-column spec memo so the
    # executors encode without re-deriving stats — and stale entries
    # (row-count drift, other mode/version) are rejected per column
    from nds_tpu import columnar
    if columnar.enabled():
        persisted = columnar.manifest_encodings(dirpath, name)
        if persisted:
            for cname, spec in persisted.items():
                if cname in cols:
                    columnar.seed_column_spec(cols[cname], spec)
    return HostTable(name, schema, cols)


def save_tables(dirpath: str, tables: dict[str, HostTable]) -> None:
    for t in tables.values():
        save_table(dirpath, t)


def load_tables(dirpath: str,
                schemas: dict[str, Schema]) -> dict[str, HostTable] | None:
    """Load every table or None if any is missing/stale."""
    out: dict[str, HostTable] = {}
    for name, schema in schemas.items():
        t = load_table(dirpath, name, schema)
        if t is None:
            return None
        out[name] = t
    return out
