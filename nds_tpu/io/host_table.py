"""Host-side columnar tables: the staging format between IO and the engine.

Everything the device engine touches is numeric. String columns are
dictionary-encoded at load: values live in a host-side sorted dictionary,
devices only see int32 codes. Because the dictionary is sorted, code order
== lexicographic order, so <,>,=,ORDER BY on strings compile to integer
compares on the MXU-friendly path (SURVEY.md §7 "hard parts": strings are
the classic reason SQL engines fall off the accelerator; this encoding
keeps them on it).

The reference has no equivalent layer — Spark DataFrames play this role
(`nds/nds_transcode.py:56-66` reads CSV into Spark). Here the layer is
explicit because the engine is ours.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from nds_tpu.engine.types import (
    DateType, DecimalType, DType, FloatType, IntType, Schema, StringType,
)


@dataclass
class HostColumn:
    """One column: numeric numpy array + optional string dictionary.

    For string columns ``values`` holds int32 codes indexing ``dictionary``
    (sorted unique values, so codes preserve lexicographic order).
    ``null_mask`` is True where the value is valid (None = all valid).
    """

    dtype: DType
    values: np.ndarray
    dictionary: np.ndarray | None = None
    null_mask: np.ndarray | None = None

    @property
    def is_string(self) -> bool:
        return self.dictionary is not None

    @property
    def nbytes(self) -> int:
        """Raw host bytes (values + null mask) — the uncompressed
        width the columnar subsystem (nds_tpu/columnar/) measures its
        encodings against."""
        return int(self.values.nbytes) + (
            0 if self.null_mask is None else int(self.null_mask.nbytes))

    def decode(self) -> np.ndarray:
        """Materialize python-visible values (strings decoded)."""
        if self.is_string:
            out = self.dictionary[np.clip(self.values, 0, len(self.dictionary) - 1)]
            if self.null_mask is not None:
                out = out.copy()
                out[~self.null_mask] = None
            return out
        return self.values


@dataclass
class HostTable:
    name: str
    schema: Schema
    columns: dict[str, HostColumn] = field(default_factory=dict)

    @property
    def nrows(self) -> int:
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())).values)

    def column(self, name: str) -> HostColumn:
        return self.columns[name]


def encode_strings(values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Sorted-dictionary encode an object array -> (int32 codes, dictionary)."""
    dictionary, codes = np.unique(values.astype(str), return_inverse=True)
    return codes.astype(np.int32), dictionary.astype(object)


def from_arrays(name: str, schema: Schema, arrays: dict[str, np.ndarray]) -> HostTable:
    """Build a HostTable from generator output ({col: numpy array}).

    Numeric/date/decimal columns pass through (decimals already scaled
    int64); object arrays are dictionary-encoded. A companion
    ``"<col>#null"`` boolean array (True = valid) becomes the column's
    null mask — how the TPC-DS generator conveys dsdgen-style NULL FKs.
    """
    cols: dict[str, HostColumn] = {}
    for f in schema:
        arr = arrays[f.name]
        mask = arrays.get(f.name + "#null")
        if mask is not None:
            mask = np.asarray(mask, dtype=bool)
            if mask.all():
                mask = None
        if isinstance(f.dtype, StringType):
            codes, dictionary = encode_strings(arr)
            cols[f.name] = HostColumn(f.dtype, codes, dictionary, mask)
        elif isinstance(f.dtype, DecimalType):
            cols[f.name] = HostColumn(f.dtype, arr.astype(np.int64), None, mask)
        elif isinstance(f.dtype, DateType):
            cols[f.name] = HostColumn(f.dtype, arr.astype(np.int32), None, mask)
        elif isinstance(f.dtype, IntType):
            cols[f.name] = HostColumn(
                f.dtype, arr.astype(f"int{f.dtype.bits}"), None, mask)
        elif isinstance(f.dtype, FloatType):
            cols[f.name] = HostColumn(
                f.dtype, arr.astype(f"float{f.dtype.bits}"), None, mask)
        else:
            cols[f.name] = HostColumn(f.dtype, arr, None, mask)
    return HostTable(name, schema, cols)
