"""Artifact integrity: per-table digest manifests + CRC-stamped JSON.

A multi-hour NDS run reads back terabytes it wrote earlier (transcoded
warehouses, cached .npz tables, the resume journal, the snapshot
manifest); a torn write or a flipped bit in any of them must surface as
a LOUD, immediately-diagnosable failure, never as silently wrong query
output or a replayed phantom phase. Two mechanisms, both stdlib-only:

- **Digest manifests** — ``write_manifest(table_dir)`` records a
  ``_manifest.json`` of ``{relpath: sha256}`` for every data file under
  a table directory (transcode writes one per table; table_cache stamps
  its .npz saves). ``verify_paths(paths, name)`` re-hashes each file on
  load and raises :class:`CorruptArtifact` — naming the file and the
  expected/actual digest — on any mismatch. Files a manifest does not
  cover (legacy warehouses, maintenance-committed versions) are skipped,
  so verification is adoptable incrementally. Gated by
  ``NDS_TPU_VERIFY_DIGESTS`` / ``io.verify_digests`` (on in tests,
  opt-in for production runs) because hashing a warehouse is not free.
  ``CorruptArtifact`` is classified DETERMINISTIC by
  ``resilience.retry``: re-reading corrupt bytes yields the same corrupt
  bytes, so retrying only triples the time to the same failure.

- **CRC-stamped JSON** — ``stamp_crc``/``check_crc`` embed a crc32 of
  the canonical (sorted-key) JSON encoding into state documents the
  harness later trusts (``bench_state.json``, ``_snapshots.json``), so
  a torn write is distinguishable from valid-but-different state and
  readers can degrade to a clean fresh start with a warning instead of
  crashing or silently splicing. ``write_json_atomic`` is the shared
  tmp+rename writer every new JSON artifact goes through (ndslint
  NDS109 flags the non-atomic pattern).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib

MANIFEST_NAME = "_manifest.json"
VERIFY_ENV = "NDS_TPU_VERIFY_DIGESTS"

# how many parent directories of a data file are searched for a
# manifest (hive-partitioned facts nest <table>/<col>=<val>/part-N)
_MANIFEST_SEARCH_DEPTH = 3


class CorruptArtifact(RuntimeError):
    """A data file's content no longer matches its recorded digest.

    Deterministic by nature (the bytes on disk are wrong; re-reading
    them cannot help), so the retry classifier never retries it."""

    def __init__(self, path: str, expected: str, actual: str):
        super().__init__(
            f"corrupt artifact {path}: sha256 expected {expected}, "
            f"got {actual}")
        self.path = path
        self.expected = expected
        self.actual = actual


# --------------------------------------------------------- verify gate

_verify_override: bool | None = None


def set_verify(on: bool | None) -> None:
    """Programmatic gate (None = defer to the env var). The power loop
    turns this on when ``io.verify_digests`` is set; tests force it via
    ``NDS_TPU_VERIFY_DIGESTS=1`` in conftest."""
    global _verify_override
    _verify_override = on


def verify_enabled() -> bool:
    if _verify_override is not None:
        return _verify_override
    return os.environ.get(VERIFY_ENV, "0") == "1"


# ------------------------------------------------------------- digests

def file_digest(path: str) -> str:
    """Streaming sha256 over the file's bytes (hex)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _data_files(table_dir: str) -> list[str]:
    out = []
    for root, _dirs, files in os.walk(table_dir):
        for f in files:
            if (f.startswith(".") or f == MANIFEST_NAME
                    or f.endswith(".tmp")):
                continue
            out.append(os.path.relpath(os.path.join(root, f), table_dir))
    return sorted(out)


def write_manifest(table_dir: str,
                   files: list[str] | None = None) -> str:
    """Record ``{relpath: sha256}`` for every data file under
    ``table_dir`` (or just ``files``, relative paths) into its
    ``_manifest.json``. Returns the manifest path."""
    rels = files if files is not None else _data_files(table_dir)
    digests = {rel: file_digest(os.path.join(table_dir, rel))
               for rel in rels}
    path = os.path.join(table_dir, MANIFEST_NAME)
    write_json_atomic(path, {"version": 1, "files": digests})
    return path


def update_manifest(table_dir: str, files: list[str]) -> str:
    """Merge digests for ``files`` (relpaths) into an existing manifest
    (create it when absent) — the incremental writer for caches that
    save one table at a time."""
    path = os.path.join(table_dir, MANIFEST_NAME)
    doc = _load_manifest(path) or {"version": 1, "files": {}}
    for rel in files:
        doc["files"][rel] = file_digest(os.path.join(table_dir, rel))
    write_json_atomic(path, doc)
    return path


def _load_manifest(path: str) -> dict | None:
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "files" not in doc:
        return None
    return doc


# manifest docs cached by (dir, mtime_ns): a 25-table warehouse load
# hits each table's manifest once per file, not once per read
_manifest_cache: dict = {}


def _manifest_for(path: str) -> tuple[dict, str] | None:
    """Walk up from a data file looking for the table-level manifest;
    returns (files dict, base dir) or None."""
    d = os.path.dirname(os.path.abspath(path))
    for _ in range(_MANIFEST_SEARCH_DEPTH):
        mpath = os.path.join(d, MANIFEST_NAME)
        try:
            mtime = os.stat(mpath).st_mtime_ns
        except OSError:
            parent = os.path.dirname(d)
            if parent == d:
                return None
            d = parent
            continue
        key = (d, mtime)
        doc = _manifest_cache.get(key)
        if doc is None:
            doc = _load_manifest(mpath)
            if doc is None:
                return None
            _manifest_cache.clear()  # one live entry per dir is enough
            _manifest_cache[key] = doc
        return doc["files"], d
    return None


def verify_manifest(table_dir: str) -> bool:
    """True when ``table_dir`` holds a manifest and EVERY recorded file
    re-hashes to its recorded digest (unconditionally — the verify
    gate does not apply: callers ask this question to decide whether
    finished work can be trusted, e.g. a resumed transcode skipping
    tables the interrupted run already completed). False on a missing/
    unreadable manifest, a missing file, or any digest mismatch."""
    doc = _load_manifest(os.path.join(table_dir, MANIFEST_NAME))
    if doc is None or not doc.get("files"):
        return False
    try:
        for rel, expected in doc["files"].items():
            if file_digest(os.path.join(table_dir, rel)) != expected:
                return False
    except OSError:
        return False
    return True


def clear_cache() -> None:
    """Drop cached manifests (tests that rewrite files in place)."""
    _manifest_cache.clear()


def verify_paths(paths: list[str] | str, name: str = "") -> None:
    """Re-hash each file against the covering manifest; raises
    CorruptArtifact on the first mismatch. No-op when verification is
    disabled; files without a covering manifest entry are skipped
    (legacy warehouses and maintenance-written versions stay loadable).
    """
    if not verify_enabled():
        return
    if isinstance(paths, str):
        paths = [paths]
    from nds_tpu.resilience import watchdog
    for p in paths:
        # per-file heartbeat: hashing a whole fact table's chunks on a
        # loaded box (several concurrent streams, cold page cache) can
        # out-wait a watchdog stall budget with no beat in between —
        # verification is work, not a hang
        watchdog.beat("engine", phase="io.read", table=name)
        found = _manifest_for(p)
        if found is None:
            continue
        files, base = found
        rel = os.path.relpath(os.path.abspath(p), base)
        expected = files.get(rel)
        if expected is None:
            continue
        actual = file_digest(p)
        if actual != expected:
            from nds_tpu.obs import metrics as obs_metrics
            obs_metrics.counter("corrupt_artifacts_total").inc()
            raise CorruptArtifact(p, expected, actual)


# --------------------------------------------------- CRC-stamped JSON

def json_crc(obj) -> str:
    """crc32 (hex) of the canonical sorted-key JSON encoding."""
    blob = json.dumps(obj, sort_keys=True, default=str)
    return f"{zlib.crc32(blob.encode()) & 0xFFFFFFFF:08x}"


def stamp_crc(doc: dict, key: str = "crc") -> dict:
    """Return ``doc`` with a crc32 of its (crc-less) content under
    ``key`` — stamp immediately before writing."""
    body = {k: v for k, v in doc.items() if k != key}
    return {**body, key: json_crc(body)}


def check_crc(doc: dict, key: str = "crc") -> bool:
    """True when the stamp matches (or the doc predates stamping —
    an unstamped doc is not evidence of a torn write)."""
    if not isinstance(doc, dict) or key not in doc:
        return True
    body = {k: v for k, v in doc.items() if k != key}
    return doc[key] == json_crc(body)


def write_json_atomic(path: str, doc, indent: int = 2) -> None:
    """tmp + rename JSON write: a crash mid-write leaves the previous
    complete file, never a torn one; readers never see partial JSON.
    The tmp is pid- AND thread-suffixed: two processes pointed at one
    path each rename a complete file into place, and two THREADS of one
    process (the watchdog's stall dump racing a SIGTERM dump — the
    PR-9 flight-recorder truncation race, ndsraces NDSR204) never
    truncate each other's stream mid-write."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=indent)
    os.replace(tmp, path)
