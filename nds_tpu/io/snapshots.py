"""Warehouse snapshot log: a minimal versioned table format.

The reference leans on Iceberg snapshots for maintenance rollback
(`nds/nds_rollback.py:46-51` calls
``system.rollback_to_timestamp``); this is the TPU-native minimal
equivalent: a ``_snapshots.json`` manifest at the warehouse root maps
each committed version to {table: [parquet files]}. Mutations write new
files and append a manifest entry; nothing is rewritten in place, so
rolling back is truncating the manifest (old files remain valid).

The manifest payload is CRC-stamped (io/integrity.py): a torn or
corrupted ``_snapshots.json`` is detected on open and degrades to the
on-disk baseline (version 0) with a warning — committed version files
are never rewritten, so the baseline is always still valid — instead
of crashing the run or silently serving a spliced version map.

Layout:
  warehouse/
    _snapshots.json                  # [{version, timestamp, tables}]
    store_sales/...                  # v0 files (transcode output)
    store_sales/_v1/part-0.parquet   # files written by version 1
"""

from __future__ import annotations

import json
import os
import time

from nds_tpu.io import integrity

MANIFEST = "_snapshots.json"


def _walk_parquet(tdir: str) -> list[str]:
    return sorted(
        os.path.relpath(os.path.join(root, f), os.path.dirname(tdir))
        for root, dirs, files in os.walk(tdir)
        if not os.path.basename(root).startswith("_v")
        for f in files if f.endswith(".parquet"))


class SnapshotLog:
    def __init__(self, warehouse_dir: str):
        self.dir = warehouse_dir
        self.path = os.path.join(warehouse_dir, MANIFEST)
        self.entries = self._read(self.path)

    @staticmethod
    def _read(path: str) -> list:
        if not os.path.exists(path):
            return []
        try:
            with open(path) as f:
                doc = json.load(f)
        except ValueError:
            doc = None
        if isinstance(doc, list):
            return doc  # legacy unstamped manifest: still trusted
        if isinstance(doc, dict) and integrity.check_crc(doc):
            return doc.get("entries", [])
        # torn/corrupt: committed version files are immutable, so the
        # on-disk baseline (version 0) is always a valid fallback —
        # counted (snapshot_resets_total) and surfaced in BenchReport
        # ``degradations`` + flight dumps, so committed maintenance
        # versions silently reverting to v0 can't hide in a long run
        print(f"WARNING: snapshot manifest {path} is torn/corrupt — "
              f"falling back to the version-0 baseline")
        from nds_tpu.obs import metrics as obs_metrics
        obs_metrics.counter("snapshot_resets_total").inc()
        return []

    def _write(self) -> None:
        integrity.write_json_atomic(
            self.path,
            integrity.stamp_crc({"version": 1, "entries": self.entries}),
            indent=1)

    def baseline(self, tables: list[str]) -> dict:
        """Version-0 file map discovered from the transcode layout."""
        return {t: _walk_parquet(os.path.join(self.dir, t))
                for t in tables
                if os.path.isdir(os.path.join(self.dir, t))}

    def current(self, tables: list[str]) -> dict:
        """{table: [abs paths]} of the latest committed version (or the
        on-disk baseline when no commits exist)."""
        if self.entries:
            m = self.entries[-1]["tables"]
        else:
            m = self.baseline(tables)
        return {t: [os.path.join(self.dir, p) for p in paths]
                for t, paths in m.items()}

    def commit(self, new_files: dict, note: str = "") -> int:
        """Append a version whose table map is the previous version's
        with ``new_files`` ({table: [rel paths]}) replacing those
        tables' files."""
        base = (dict(self.entries[-1]["tables"]) if self.entries
                else self.baseline(list(new_files)))
        # baseline() above only covers the mutated tables when this is
        # the first commit; fill in every other on-disk table so the
        # manifest is complete
        for t in os.listdir(self.dir):
            tdir = os.path.join(self.dir, t)
            if os.path.isdir(tdir) and t not in base:
                files = _walk_parquet(tdir)
                if files:
                    base[t] = files
        base.update(new_files)
        version = (self.entries[-1]["version"] + 1 if self.entries
                   else 1)
        self.entries.append({"version": version,
                             "timestamp": time.time(),
                             "note": note, "tables": base})
        self._write()
        return version

    def commit_delta(self, table: str, new_rel_paths: list,
                     note: str = "") -> int:
        """Append a version whose file list for ``table`` is the
        previous version's files PLUS ``new_rel_paths`` (delta lineage:
        base files + every committed delta artifact, in commit order —
        the reader replays them ascending). This append IS the atomic
        commit point: until the stamped manifest lands, the delta files
        are unreferenced and the reader serves the prior version."""
        prev = (self.entries[-1]["tables"] if self.entries
                else self.baseline([table]))
        paths = list(prev.get(table, [])) + [
            p for p in new_rel_paths if p not in prev.get(table, [])]
        return self.commit({table: paths}, note=note)

    def has_note(self, note: str) -> bool:
        """True when a committed version carries ``note`` — maintenance
        resume uses this to detect a crash that landed AFTER a refresh
        function's snapshot commit but BEFORE its journal record (the
        function's effects are durable; re-running would double-apply)."""
        return any(e.get("note") == note for e in self.entries)

    def rollback_to_timestamp(self, ts: float) -> int | None:
        """Drop every version committed after ``ts``
        (`nds/nds_rollback.py:46-51` semantics). Returns the surviving
        version number, or None if rolled back to the baseline."""
        self.entries = [e for e in self.entries if e["timestamp"] <= ts]
        self._write()
        return self.entries[-1]["version"] if self.entries else None

    def version_dir(self, table: str, version: int) -> str:
        d = os.path.join(self.dir, table, f"_v{version}")
        os.makedirs(d, exist_ok=True)
        return d
