"""Query-result Parquet IO for differential validation.

The reference power run can persist each query's output
(`nds/nds_power.py:132-135` df.write.save) and the validator reads both
CPU and GPU outputs back (`nds/nds_validate.py:82-83`). Same contract
here: results from either backend round-trip through Parquet so
`nds_tpu.nds_h.validate` can diff them.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from nds_tpu.engine.cpu_exec import ResultTable
from nds_tpu.engine.types import DateType, DecimalType


def result_to_arrow(result: ResultTable) -> pa.Table:
    arrays = []
    names = []
    for i, (name, arr, dt, valid) in enumerate(zip(
            result.names, result.cols, result.dtypes, result.valids)):
        names.append(f"{name}#{i}" if result.names.count(name) > 1 else name)
        mask = None if valid is None else ~np.asarray(valid)
        if isinstance(dt, DecimalType):
            vals = np.asarray(arr, dtype=np.float64) / 10 ** dt.scale
            arrays.append(pa.array(vals, mask=mask))
        elif isinstance(dt, DateType):
            arrays.append(pa.array(
                np.asarray(arr, dtype=np.int32), type=pa.int32(),
                mask=mask).cast(pa.date32()))
        elif arr.dtype == object:
            arrays.append(pa.array(
                [None if (mask is not None and mask[j]) else str(arr[j])
                 for j in range(len(arr))], type=pa.string()))
        else:
            arrays.append(pa.array(arr, mask=mask))
    return pa.Table.from_arrays(arrays, names=names)


def write_result(result: ResultTable, out_dir: str) -> str:
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "part-0.parquet")
    pq.write_table(result_to_arrow(result), path)
    return path


def result_digest(result) -> str | None:
    """Stable sha256 (hex, 16 chars) over a ResultTable's schema +
    values + null masks — the per-query fingerprint the resume journal
    records (resilience/journal.QueryJournal) so the soak gate can
    prove an interrupted-then-resumed run produced byte-identical
    results to an uninterrupted one. None for resultless statements
    (DML) or anything that does not quack like a ResultTable."""
    import hashlib
    if result is None or not hasattr(result, "cols"):
        return None
    h = hashlib.sha256()
    try:
        for name, arr, dt, valid in zip(result.names, result.cols,
                                        result.dtypes, result.valids):
            h.update(f"{name}|{dt}|".encode())
            a = np.asarray(arr)
            if a.dtype == object:
                mask = None if valid is None else ~np.asarray(valid)
                for j in range(len(a)):
                    if mask is not None and mask[j]:
                        h.update(b"\x00N")
                    else:
                        h.update(str(a[j]).encode())
                    h.update(b"\x1f")
            else:
                h.update(np.ascontiguousarray(a).tobytes())
            if valid is not None:
                h.update(np.ascontiguousarray(
                    np.asarray(valid, dtype=np.uint8)).tobytes())
            h.update(b"\x1e")
    except Exception:  # noqa: BLE001 - a digest must never fail a query
        return None
    return h.hexdigest()[:16]


def read_result(out_dir: str):
    """-> pandas DataFrame (dates as date32 -> object, fine for diffing)."""
    paths = sorted(os.path.join(out_dir, f) for f in os.listdir(out_dir)
                   if f.endswith(".parquet"))
    return pa.concat_tables([pq.read_table(p) for p in paths]).to_pandas()
