"""Round benchmark: NDS-H (22 queries) + NDS (103 statements — the 99
TPC-DS templates with q14/q23/q24/q39 split into _part1/_part2) power
runs, TPU engine vs CPU oracle.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} as the
LAST line of stdout (the driver's contract). That line is the combined
two-leg power total; per-leg metrics (`nds_h_sf*_power_total`,
`nds_sf*_power_total`) are carried in its "legs" object and are also
printed as standalone partial lines while each leg runs, so a timeout
mid-run still leaves the best-known metric on stdout. A "per_query"
block carries every completed query's device seconds plus the worst-5
regressions vs BASELINE.json's optional "per_query" map (computed by
nds_tpu/obs/analyze.diff_times), so rounds are comparable query-by-
query, not only by the opaque total.

Methodology follows the reference power run (bracketed wall-clock around
execute+collect per query, `nds/PysparkBenchReport.py:87-105`): each
query compiles once untimed (AOT — the reference's warmed-JVM analog),
then runs timed on the JAX device engine (real TPU chip when available),
then on the CPU oracle as the baseline — the reference publishes no
numbers (BASELINE.md), so CPU wall-clock is the denominator.

Budget-robust by design (a timeout must still yield a metric):
- generated data persists under .bench_data/ and reloads on re-runs;
- the XLA persistent compilation cache (.xla_cache/) makes compiles
  one-time costs across processes;
- results bank incrementally per query and SIGTERM/SIGINT prints the
  final JSON from whatever has completed, pairing device and CPU times
  over the same completed-query set.

value = device power-run total seconds; vs_baseline = cpu_total /
device_total over completed queries (>1 means the TPU engine wins).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

# Scale factors balance signal vs budget: large enough that device
# compute dominates the per-query tunnel RTT floor, small enough that
# the CPU-oracle denominator finishes within the driver budget; data
# (.bench_data/) and XLA executables (.xla_cache/) persist across runs,
# so the driver's timed run skips datagen and compiles. Round 5 moved
# both legs to SF1 (VERDICT r4: SF0.1/0.3 times are tunnel-RTT noise).
SF_H = float(os.environ.get("BENCH_SF", "1"))
SF_DS = float(os.environ.get("BENCH_NDS_SF", "1"))
HERE = os.path.dirname(os.path.abspath(__file__))
DATA_ROOT = os.environ.get("BENCH_DATA", os.path.join(HERE, ".bench_data"))
# which legs run (comma list); the NDS-H leg runs first so a budget
# kill still records the historical headline metric
LEGS = os.environ.get("BENCH_LEGS", "nds_h,nds").split(",")

# banked per-query results: (leg, qname) -> {"device_s": .., "cpu_s": ..}
# qname is a string: "7", or "14_part1"/"14_part2" for the four
# two-statement TPC-DS templates (103 executable statements per stream,
# reference `nds/nds_gen_query_stream.py:91-103` + `nds_power.py:50-77`)
BANK: dict[tuple, dict] = {}
LEG_TOTALS: dict[str, int] = {}  # leg -> queries_total
_done = False


def _leg_line(leg: str, metric: str) -> dict:
    paired = {k: r for k, r in BANK.items()
              if k[0] == leg and "device_s" in r and "cpu_s" in r}
    dev = sum(r["device_s"] for r in paired.values())
    cpu = sum(r["cpu_s"] for r in paired.values())
    return {
        "metric": metric,
        "value": round(dev, 4),
        "unit": "s",
        "vs_baseline": round(cpu / dev, 4) if dev else 0.0,
        "queries_completed": len(paired),
        "queries_total": LEG_TOTALS.get(leg, 0),
    }


def _metric_name(leg: str) -> str:
    return (f"nds_h_sf{SF_H:g}_power_total" if leg == "nds_h"
            else f"nds_sf{SF_DS:g}_power_total")


def _per_query_block() -> dict | None:
    """Worst-5 per-query regressions vs BASELINE.json's optional
    ``per_query`` map ({"leg:qname": seconds}), via the run-analysis
    diff code (nds_tpu/obs/analyze.py) — plus the current per-query
    device times, so a BENCH round is a promotable baseline and not an
    opaque scalar. Never raises: this runs inside the SIGTERM path."""
    try:
        cur = {f"{leg}:{qn}": round(r["device_s"], 4)
               for (leg, qn), r in BANK.items() if "device_s" in r}
        if not cur:
            return None
        block: dict = {"times": cur}
        try:
            with open(os.path.join(HERE, "BASELINE.json")) as f:
                base = json.load(f).get("per_query") or {}
        except (OSError, ValueError):
            base = {}
        if base:
            from nds_tpu.obs.analyze import diff_times
            d = diff_times({q: s * 1000.0 for q, s in base.items()},
                           {q: s * 1000.0 for q, s in cur.items()},
                           pct=10.0, abs_ms=50.0)
            block["baseline_compared"] = (
                len(d["regressions"]) + len(d["improvements"])
                + len(d["noise"]))
            block["worst_regressions"] = d["regressions"][:5]
            block["improvements_n"] = len(d["improvements"])
        return block
    except Exception:  # noqa: BLE001 - metric line must always emit
        return None


def _combined_dict() -> dict:
    legs = {}
    dev = cpu = completed = total = 0
    for leg in LEGS:
        line = _leg_line(leg, _metric_name(leg))
        legs[_metric_name(leg)] = line
        dev += line["value"]
        cpu += line["value"] * line["vs_baseline"]
        completed += line["queries_completed"]
        total += line["queries_total"]
    out = {
        "metric": "nds+nds_h_power_total",
        "value": round(dev, 4),
        "unit": "s",
        "vs_baseline": round(cpu / dev, 4) if dev else 0.0,
        "queries_completed": completed,
        "queries_total": total,
        "legs": legs,
    }
    pq = _per_query_block()
    if pq:
        out["per_query"] = pq
    return out


def _combined_line() -> str:
    return json.dumps(_combined_dict())


def _emit_final() -> None:
    global _done
    if _done:
        return
    _done = True
    print(_combined_line(), flush=True)


def _on_term(signum, frame):
    print(f"[bench] signal {signum}: emitting partial metric "
          f"({len(BANK)} queries banked)", file=sys.stderr, flush=True)
    _emit_final()
    sys.exit(0)


def _load_or_gen(leg: str):
    from nds_tpu.io import table_cache
    from nds_tpu.io.host_table import from_arrays
    if leg == "nds_h":
        from nds_tpu.datagen import tpch as gen
        from nds_tpu.nds_h.schema import get_schemas
        sf = SF_H
    else:
        from nds_tpu.datagen import tpcds as gen
        from nds_tpu.nds.schema import get_schemas
        sf = SF_DS
    schemas = get_schemas()
    data_dir = os.path.join(DATA_ROOT, f"{leg}_sf{sf:g}")
    # legacy layout from earlier rounds (nds_h only, no leg prefix)
    legacy = os.path.join(DATA_ROOT, f"sf{sf:g}")
    if leg == "nds_h" and not os.path.isdir(data_dir) \
            and os.path.isdir(legacy):
        data_dir = legacy
    cached = table_cache.load_tables(data_dir, schemas)
    if cached is not None:
        print(f"[bench] {leg}: loaded SF{sf:g} data from {data_dir}",
              file=sys.stderr, flush=True)
        return cached
    print(f"[bench] {leg}: generating SF{sf:g} data...", file=sys.stderr,
          flush=True)
    tables = {t: from_arrays(t, schemas[t], gen.gen_table(t, sf))
              for t in schemas}
    table_cache.save_tables(data_dir, tables)
    return tables


def _statements(leg: str, qn: int, sql: str) -> list[str]:
    if leg == "nds_h":
        from nds_tpu.nds_h.streams import statements
        return list(statements(qn, sql))
    return [s.strip() for s in sql.split(";") if s.strip()]


def _run_query(session, stmts: list[str]) -> float:
    t0 = time.perf_counter()
    for s in stmts:
        session.sql(s)
    return time.perf_counter() - t0


# -------------------------------------------------- CPU-oracle time bank
#
# The 121-query CPU-oracle denominator costs more wall-clock than the
# device leg itself; re-deriving it every driver run is what pushed
# round 3 past the budget (VERDICT r3 "what's missing" #1). CPU times
# are a property of (suite, SF, query, host) only — the deterministic
# generators make the data identical across runs — so they bank to
# DATA_ROOT and reload. BENCH_CPU=fresh forces re-measurement.

def _cpu_bank_path(leg: str) -> str:
    sf = SF_H if leg == "nds_h" else SF_DS
    return os.path.join(DATA_ROOT, f"cpu_times_{leg}_sf{sf:g}.json")


# ------------------------------------------- device-time bank (stale
# fallback): the remote chip tunnel can be down for hours (round 4 lost
# most of a day to one outage). Completed per-query device times
# persist here; when the device is unreachable at startup the bench
# emits the banked metric labeled "stale_device_times": true instead of
# hanging the driver in jax initialization.

def _dev_bank_path(leg: str) -> str:
    sf = SF_H if leg == "nds_h" else SF_DS
    return os.path.join(DATA_ROOT, f"device_times_{leg}_sf{sf:g}.json")


def _rows_fingerprint(tables) -> dict:
    return {t: tb.nrows for t, tb in tables.items()}


_BANK_DEVICE_TIMES = True  # cleared when the timed leg runs off-TPU


def _purge_presplit(times: dict) -> dict:
    """Round-4 banks timed the two-statement templates as one combined
    key ('14'); merging part keys next to it would double-count the
    template in a later stale emit — the split times win."""
    for base in [k for k in times
                 if "_part" not in k and f"{k}_part1" in times]:
        del times[base]
    return times


def _save_dev_bank(leg: str, rows: dict) -> None:
    if not _BANK_DEVICE_TIMES:
        return  # never bank CPU wall-clocks as device_s (ADVICE r4)
    path = _dev_bank_path(leg)
    # merge with what's on disk: a partial run must refine, never
    # destroy, the last complete run's banked times (the stale
    # fallback's whole value)
    try:
        with open(path) as f:
            bank = json.load(f)
        if "times" not in bank:  # legacy flat {qname: s} format
            bank = {"rows": None, "times": bank}
    except (OSError, ValueError):
        bank = {"rows": None, "times": {}}
    if bank["rows"] is not None and bank["rows"] != rows:
        bank = {"rows": None, "times": {}}  # data changed: restart bank
    bank["rows"] = rows
    bank["times"].update(
        {qn: r["device_s"] for (lg, qn), r in BANK.items()
         if lg == leg and "device_s" in r})
    _purge_presplit(bank["times"])
    with open(path + ".tmp", "w") as f:
        json.dump(bank, f)
    os.replace(path + ".tmp", path)


def _probe_backend(timeout_s: int = 120) -> str:
    """Active jax backend ('tpu'/'cpu'/...) or '' when unreachable.
    jax.devices() blocks forever on a dead tunnel, and a failed TPU
    plugin silently falls back to CPU (ADVICE r4) — so probe in a
    subprocess with a hard timeout AND verify the backend kind, never
    just device count."""
    import subprocess
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices(); "
             "print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s)
        return proc.stdout.strip().splitlines()[-1] if proc.stdout else ""
    except Exception:  # noqa: BLE001
        return ""


def _load_bank_pair(leg: str, dev_path: str, cpu_path: str) -> int:
    """Pair one device bank with its cpu bank into BANK; returns pairs
    added. Fingerprint discipline (ADVICE r4): when both banks carry a
    rows fingerprint they must match; a legacy device bank without one
    pairs only against same-SF cpu times (same path key) and is
    labeled by the caller."""
    try:
        with open(dev_path) as f:
            dev_bank = json.load(f)
        if "times" not in dev_bank:
            dev_bank = {"rows": None, "times": dev_bank}
    except (OSError, ValueError):
        return 0
    try:
        with open(cpu_path) as f:
            cpu_bank = json.load(f)
    except (OSError, ValueError):
        return 0
    if dev_bank["rows"] is not None \
            and cpu_bank.get("rows") not in (None, dev_bank["rows"]):
        return 0  # regenerated data: refuse the mismatched ratio
    added = 0
    cpu_times = _purge_presplit(dict(cpu_bank.get("times", {})))
    _purge_presplit(dev_bank["times"])
    for qn, ds in dev_bank["times"].items():
        if qn in cpu_times:
            BANK[(leg, qn)] = {"device_s": ds, "cpu_s": cpu_times[qn]}
            added += 1
    return added


def _emit_stale_from_banks() -> bool:
    """Load banked device+cpu times and emit the combined line with an
    explicit staleness marker. Returns False if no banked device leg
    exists (nothing honest to report). Falls back to banks at OTHER
    scale factors (earlier rounds' runs) when the configured SF has
    none, relabeling the metric accordingly."""
    import glob
    any_pairs = False
    fallback_sf = {}
    for leg in LEGS:
        n = _load_bank_pair(leg, _dev_bank_path(leg), _cpu_bank_path(leg))
        if n == 0:
            # any completed real-chip run at another SF beats silence
            pat = os.path.join(DATA_ROOT, f"device_times_{leg}_sf*.json")
            for dev_path in sorted(glob.glob(pat), reverse=True):
                sf = os.path.basename(dev_path)[
                    len(f"device_times_{leg}_sf"):-len(".json")]
                cpu_path = os.path.join(
                    DATA_ROOT, f"cpu_times_{leg}_sf{sf}.json")
                if _load_bank_pair(leg, dev_path, cpu_path):
                    fallback_sf[leg] = sf
                    break
        any_pairs = any_pairs or any(k[0] == leg for k in BANK)
    if not any_pairs:
        return False
    line = _combined_dict()
    line["stale_device_times"] = True
    if fallback_sf:
        line["stale_fallback_sf"] = fallback_sf
    line["note"] = ("TPU unreachable at bench time; values are the "
                    "last completed real-chip run's banked per-query "
                    "times")
    print(json.dumps(line), flush=True)
    return True


def _load_cpu_bank(leg: str, tables) -> dict:
    if os.environ.get("BENCH_CPU", "auto") == "fresh":
        return {}
    try:
        with open(_cpu_bank_path(leg)) as f:
            bank = json.load(f)
    except (OSError, ValueError):
        return {}
    # fingerprint: banked times are only valid for identical data
    rows = {t: tb.nrows for t, tb in tables.items()}
    if bank.get("rows") != rows:
        return {}
    return bank.get("times", {})


def _save_cpu_bank(leg: str, tables, times: dict) -> None:
    path = _cpu_bank_path(leg)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"rows": {t: tb.nrows for t, tb in tables.items()},
                   "times": _purge_presplit(dict(times))}, f)
    os.replace(tmp, path)


# transient transport failures from the remote-attached chip extend
# beyond compiles (round 3 lost q22 to a BrokenPipeError mid-transfer):
# any failure matching these marks retries instead of failing the query
_TRANSIENT = ("brokenpipe", "unexpected eof", "response body closed",
              "connection", "unavailable", "deadline", "transport",
              "remote_compile", "socket")


def _is_transient(exc: BaseException) -> bool:
    s = f"{type(exc).__name__}: {exc}".lower()
    return any(t in s for t in _TRANSIENT)


def _cleanup_views(session, stmts: list[str]) -> None:
    """Best-effort drop of any views a half-completed statement list
    left behind, so a retry can replay CREATE VIEW statements."""
    for s in stmts:
        if s.lstrip().lower().startswith("drop view"):
            try:
                session.sql(s)
            except Exception:  # noqa: BLE001
                pass


def _leg_units(leg: str) -> list:
    """[(qname, [stmt, ...]), ...] — one unit per TIMED query. NDS
    two-statement templates contribute one unit per statement
    (query14_part1/query14_part2 timed separately, the reference's
    `nds_power.py:50-77` contract → 103 NDS units); NDS-H keeps one
    unit per template with q15's create-view/select/drop statements
    timed together."""
    units = []

    def _render(qn, streams):
        # a broken template must cost one unit, not the whole bench
        # (this runs at startup, before any metric can be emitted)
        try:
            return _statements(leg, qn, streams.render_query(qn))
        except Exception as exc:  # noqa: BLE001
            print(f"[bench] {leg} q{qn}: template render failed "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr,
                  flush=True)
            return None

    if leg == "nds_h":
        from nds_tpu.nds_h import streams
        for qn in range(1, 23):
            units.append((str(qn), _render(qn, streams)))
        return units
    from nds_tpu.nds import streams
    qids = streams.available_templates()
    # budget insurance: the handful of giant-program templates
    # (multi-hour XLA compiles when the persistent cache is cold)
    # run LAST so a budget kill mid-compile still banks the other
    # queries. Pure ordering — every template still runs, and
    # with a warm cache the order is irrelevant.
    defer = {int(x) for x in os.environ.get(
        "BENCH_DEFER", "39,59,67,78").split(",") if x}
    for qn in ([q for q in qids if q not in defer]
               + [q for q in qids if q in defer]):
        stmts = _render(qn, streams)
        if stmts is None or len(stmts) == 1:
            units.append((str(qn), stmts))
        else:
            for i, s in enumerate(stmts, 1):
                units.append((f"{qn}_part{i}", [s]))
    return units


def _run_leg(leg: str) -> None:
    from nds_tpu.engine.device_exec import make_device_factory
    from nds_tpu.engine.session import Session

    mk = Session.for_nds_h if leg == "nds_h" else Session.for_nds
    units = _leg_units(leg)
    tables = _load_or_gen(leg)
    rows = _rows_fingerprint(tables)
    dev = mk(make_device_factory())
    cpu = mk()
    for t in tables.values():
        dev.register_table(t)
        cpu.register_table(t)

    cpu_bank = _load_cpu_bank(leg, tables)
    if cpu_bank:
        print(f"[bench] {leg}: {len(cpu_bank)} banked cpu-oracle times "
              f"from {_cpu_bank_path(leg)}", file=sys.stderr, flush=True)

    for qn, stmts in units:
        if stmts is None:  # template failed to render at startup
            continue
        # one broken query must not cost the rest of the run (the
        # reference's --allow_failure mode, `nds/nds_power.py:391-393`)
        try:
            # untimed warmup: AOT compile + one execution per statement.
            # The remote compile service drops connections under long
            # compiles ("response body closed" / "Unexpected EOF") —
            # transient, so retry PER STATEMENT (retrying the whole
            # list would replay a succeeded CREATE VIEW and turn the
            # transient into a hard 'view already exists')
            for s in stmts:
                for attempt in range(3):
                    try:
                        dev.sql(s)
                        break
                    except Exception as exc:  # noqa: BLE001
                        if attempt == 2 or not _is_transient(exc):
                            raise
                        print(f"[bench] {leg} q{qn}: transient compile "
                              f"error, retrying statement",
                              file=sys.stderr, flush=True)
            # timed run, with transient-transport retry (the whole
            # statement list replays; drops run first so re-created
            # views don't collide)
            for attempt in range(3):
                try:
                    dev_s = _run_query(dev, stmts)
                    break
                except Exception as exc:  # noqa: BLE001
                    if attempt == 2 or not _is_transient(exc):
                        raise
                    print(f"[bench] {leg} q{qn}: transient error in "
                          f"timed run ({type(exc).__name__}), retrying",
                          file=sys.stderr, flush=True)
                    _cleanup_views(dev, stmts)
            BANK.setdefault((leg, qn), {})["device_s"] = dev_s
            _save_dev_bank(leg, rows)
            # engine-side perf accounting (compile/execute/materialize),
            # read through the span-fed accessor (nds_tpu/obs)
            from nds_tpu import obs
            dev_ex = dev._executor_factory(dev.tables)
            tm = obs.query_timings(dev_ex)
            banked = cpu_bank.get(qn)
            if banked is not None:
                cpu_s = float(banked)
            else:
                cpu_s = _run_query(cpu, stmts)
                cpu_bank[qn] = cpu_s
                _save_cpu_bank(leg, tables, cpu_bank)
            BANK[(leg, qn)]["cpu_s"] = cpu_s
        except Exception as exc:  # noqa: BLE001
            BANK.pop((leg, qn), None)
            print(f"[bench] {leg} q{qn}: FAILED {type(exc).__name__}: "
                  f"{exc}", file=sys.stderr, flush=True)
            continue
        print(f"[bench] {leg} q{qn}: tpu {dev_s*1000:.0f} ms "
              f"(exec {tm.get('execute_ms', 0):.0f} "
              f"mat {tm.get('materialize_ms', 0):.0f} "
              f"{tm.get('scan_gbps', 0):.1f}GB/s) | "
              f"cpu {cpu_s*1000:.0f} ms"
              f"{' [banked]' if banked is not None else ''}",
              file=sys.stderr, flush=True)
        # the full combined partial (not a leg-scoped line): a hard kill
        # can defer the SIGTERM handler inside XLA C++, so the last
        # printed line must already carry every completed leg
        print(_combined_line(), flush=True)


# exit codes for non-fresh metrics (ROADMAP item 2: a banked number
# must be a LOUD failure, not a silently emitted line — BENCH_r04/r05
# shipped stale metrics with exit 0 and nobody noticed for two rounds)
EXIT_STALE_METRIC = 4        # emitted, but from banked device times
EXIT_NO_METRIC = 5           # device unreachable and no bank either


def main() -> int:
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    # totals for EVERY leg up front — and before the (multi-second,
    # kill-prone) TPU init below: a kill at any point must still count
    # every leg's queries in queries_total (else a 22/22 nds_h-only
    # partial reads as a complete 125-unit run). NDS counts 103 units
    # (the four two-statement templates split into parts).
    for leg in LEGS:
        LEG_TOTALS[leg] = len(_leg_units(leg))

    # the probe guards two failure modes: a dead tunnel (jax init hangs
    # forever) and a failed TPU plugin silently falling back to CPU
    # (which would bank CPU wall-clocks as device_s — ADVICE r4)
    global _BANK_DEVICE_TIMES
    backend = _probe_backend()
    want = os.environ.get("BENCH_BACKEND", "tpu")
    _BANK_DEVICE_TIMES = backend == "tpu" == want
    if backend != want:
        print(f"[bench] device backend {backend or 'UNREACHABLE'!r} != "
              f"{want!r} (tunnel down or plugin fell back) — emitting "
              "banked metric from the last completed real-chip run",
              file=sys.stderr, flush=True)
        if _emit_stale_from_banks():
            # the stale line still prints (a labeled partial beats
            # silence for a human reader) but the RUN FAILS: CI and
            # the round record must never book a banked number as a
            # fresh measurement
            print(f"[bench] exit {EXIT_STALE_METRIC}: stale/banked "
                  f"device times are not a fresh metric",
                  file=sys.stderr, flush=True)
            return EXIT_STALE_METRIC
        print("[bench] no banked real-chip run available either — "
              "no honest metric to emit", file=sys.stderr, flush=True)
        line = _combined_dict()
        line["device_unreachable"] = True
        print(json.dumps(line), flush=True)
        return EXIT_NO_METRIC

    from nds_tpu.utils.xla_cache import enable as enable_xla_cache
    cache_dir = enable_xla_cache()
    print(f"[bench] xla cache: {cache_dir}", file=sys.stderr, flush=True)

    import jax
    print(f"[bench] backend: {jax.default_backend()} {jax.devices()}",
          file=sys.stderr, flush=True)

    for leg in LEGS:
        _run_leg(leg)

    _emit_final()
    return 0


if __name__ == "__main__":
    sys.exit(main())
