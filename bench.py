"""Round benchmark: NDS-H power run, TPU engine vs CPU oracle.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} (last
line of stdout).

Methodology follows the reference power run (bracketed wall-clock around
execute+collect per query, `nds/PysparkBenchReport.py:87-105`): each of
the 22 qualification queries compiles once (untimed, AOT — the
reference's warmed-JVM analog), then runs timed on the JAX device engine
(real TPU chip when available), then on the CPU oracle as the baseline —
the reference publishes no numbers (BASELINE.md), so CPU wall-clock is
the denominator.

Budget-robust by design (a timeout must still yield a metric):
- generated data persists under .bench_data/ and reloads on re-runs;
- the XLA persistent compilation cache (.xla_cache/) makes compiles
  one-time costs across processes;
- results bank incrementally per query and SIGTERM/SIGINT prints the
  final JSON from whatever has completed, pairing device and CPU times
  over the same completed-query set.

value = device power-run total seconds; vs_baseline = cpu_total /
device_total over completed queries (>1 means the TPU engine wins).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

# SF0.3 balances signal vs budget: large enough that device compute
# dominates the per-query tunnel RTT floor (~0.3s), small enough that
# the CPU-oracle denominator still finishes within the driver budget;
# data (.bench_data/) and XLA executables (.xla_cache/) persist across
# runs, so the driver's timed run skips datagen and compiles
SF = float(os.environ.get("BENCH_SF", "0.3"))
HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.environ.get(
    "BENCH_DATA", os.path.join(HERE, ".bench_data", f"sf{SF:g}"))

# banked per-query results: qn -> {"device_s": float, "cpu_s": float}
BANK: dict[int, dict] = {}
_done = False


def _partial_line() -> str:
    """The running metric over completed queries. Printed after EVERY
    query (last line of stdout wins), so a hard kill mid-compile — where
    the SIGTERM handler can be deferred inside XLA C++ — still leaves a
    parseable metric on stdout."""
    paired = {qn: r for qn, r in BANK.items()
              if "device_s" in r and "cpu_s" in r}
    dev_total = sum(r["device_s"] for r in paired.values())
    cpu_total = sum(r["cpu_s"] for r in paired.values())
    return json.dumps({
        "metric": f"nds_h_sf{SF:g}_power_total",
        "value": round(dev_total, 4),
        "unit": "s",
        "vs_baseline": (round(cpu_total / dev_total, 4)
                        if dev_total else 0.0),
        "queries_completed": len(paired),
        "queries_total": 22,
    })


def _emit_final() -> None:
    global _done
    if _done:
        return
    _done = True
    print(_partial_line(), flush=True)


def _on_term(signum, frame):
    print(f"[bench] signal {signum}: emitting partial metric "
          f"({len(BANK)} queries banked)", file=sys.stderr, flush=True)
    _emit_final()
    sys.exit(0)


def _load_or_gen_data():
    from nds_tpu.datagen import tpch
    from nds_tpu.io import table_cache
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds_h.schema import get_schemas
    schemas = get_schemas()
    cached = table_cache.load_tables(DATA_DIR, schemas)
    if cached is not None:
        print(f"[bench] loaded SF{SF:g} data from {DATA_DIR}",
              file=sys.stderr, flush=True)
        return cached
    print(f"[bench] generating SF{SF:g} data...", file=sys.stderr,
          flush=True)
    tables = {t: from_arrays(t, schemas[t], tpch.gen_table(t, SF))
              for t in schemas}
    table_cache.save_tables(DATA_DIR, tables)
    return tables


def _run_query(session, qn: int, sql: str) -> float:
    from nds_tpu.nds_h.streams import statements
    t0 = time.perf_counter()
    for s in statements(qn, sql):
        session.sql(s)
    return time.perf_counter() - t0


def main() -> None:
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    from nds_tpu.utils.xla_cache import enable as enable_xla_cache
    cache_dir = enable_xla_cache()
    print(f"[bench] xla cache: {cache_dir}", file=sys.stderr, flush=True)

    from nds_tpu.engine.device_exec import make_device_factory
    from nds_tpu.engine.session import Session
    from nds_tpu.nds_h import streams

    tables = _load_or_gen_data()

    import jax
    print(f"[bench] backend: {jax.default_backend()} {jax.devices()}",
          file=sys.stderr, flush=True)

    dev = Session.for_nds_h(make_device_factory())
    cpu = Session.for_nds_h()
    for t in tables.values():
        dev.register_table(t)
        cpu.register_table(t)

    dev_ex = None
    for qn in range(1, 23):
        sql = streams.render_query(qn)
        # untimed warmup: AOT compile + one execution per statement
        for s in streams.statements(qn, sql):
            dev.sql(s)
        dev_s = _run_query(dev, qn, sql)
        BANK.setdefault(qn, {})["device_s"] = dev_s
        # engine-side perf accounting (compile vs execute vs materialize)
        if dev_ex is None:
            dev_ex = dev._executor_factory(dev.tables)
        tm = dict(dev_ex.last_timings)
        cpu_s = _run_query(cpu, qn, sql)
        BANK[qn]["cpu_s"] = cpu_s
        print(f"[bench] q{qn}: tpu {dev_s*1000:.0f} ms "
              f"(exec {tm.get('execute_ms', 0):.0f} "
              f"mat {tm.get('materialize_ms', 0):.0f}) | "
              f"cpu {cpu_s*1000:.0f} ms", file=sys.stderr, flush=True)
        print(_partial_line(), flush=True)

    _emit_final()


if __name__ == "__main__":
    sys.exit(main())
