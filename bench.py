"""Round benchmark: NDS-H power run, TPU engine vs CPU oracle.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Methodology follows the reference power run (bracketed wall-clock around
execute+collect per query, `nds/PysparkBenchReport.py:87-105`): the 22
qualification queries run on the JAX device engine (real TPU chip when
available) after one untimed warmup pass (steady-state compile cache, the
reference's warmed-JVM analog), and the same stream runs on the CPU
oracle as the baseline — the reference publishes no numbers
(BASELINE.md), so CPU wall-clock is the denominator.

value = device power-run total seconds; vs_baseline = cpu_total /
device_total (>1 means the TPU engine beats the CPU baseline).
"""

from __future__ import annotations

import json
import os
import sys
import time

SF = float(os.environ.get("BENCH_SF", "0.1"))
DATA_DIR = os.environ.get("BENCH_DATA", os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".bench_data",
    f"sf{SF:g}"))


def _gen_data():
    from nds_tpu.datagen import tpch
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds_h.schema import get_schemas
    schemas = get_schemas()
    return {t: from_arrays(t, schemas[t], tpch.gen_table(t, SF))
            for t in schemas}


def _power_run(session, label: str, warmup: int = 1):
    from nds_tpu.nds_h import streams
    times = {}
    for qn in range(1, 23):
        sql = streams.render_query(qn)
        stmts = ([s for s in sql.split(";") if s.strip()]
                 if qn == 15 else [sql])
        for _ in range(warmup):
            for s in stmts:
                session.sql(s)
        t0 = time.perf_counter()
        for s in stmts:
            session.sql(s)
        times[qn] = time.perf_counter() - t0
        print(f"[bench] {label} q{qn}: {times[qn]*1000:.0f} ms",
              file=sys.stderr, flush=True)
    return times


def main() -> None:
    from nds_tpu.engine.device_exec import make_device_factory
    from nds_tpu.engine.session import Session

    print(f"[bench] generating SF{SF:g} data...", file=sys.stderr,
          flush=True)
    tables = _gen_data()

    import jax
    print(f"[bench] backend: {jax.default_backend()} {jax.devices()}",
          file=sys.stderr, flush=True)
    dev = Session.for_nds_h(make_device_factory())
    for t in tables.values():
        dev.register_table(t)
    # q15 creates/drops a view per pass; warmup handled inside _power_run
    dev_times = _power_run(dev, "tpu", warmup=1)
    dev_total = sum(dev_times.values())

    cpu = Session.for_nds_h()
    for t in tables.values():
        cpu.register_table(t)
    cpu_times = _power_run(cpu, "cpu-oracle", warmup=0)
    cpu_total = sum(cpu_times.values())

    result = {
        "metric": f"nds_h_sf{SF:g}_power_total",
        "value": round(dev_total, 4),
        "unit": "s",
        "vs_baseline": round(cpu_total / dev_total, 4) if dev_total else 0.0,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
