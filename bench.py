"""Round benchmark: NDS-H (22 queries) + NDS (99 queries) power runs,
TPU engine vs CPU oracle.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} as the
LAST line of stdout (the driver's contract). That line is the combined
two-leg power total; per-leg metrics (`nds_h_sf*_power_total`,
`nds_sf*_power_total`) are carried in its "legs" object and are also
printed as standalone partial lines while each leg runs, so a timeout
mid-run still leaves the best-known metric on stdout.

Methodology follows the reference power run (bracketed wall-clock around
execute+collect per query, `nds/PysparkBenchReport.py:87-105`): each
query compiles once untimed (AOT — the reference's warmed-JVM analog),
then runs timed on the JAX device engine (real TPU chip when available),
then on the CPU oracle as the baseline — the reference publishes no
numbers (BASELINE.md), so CPU wall-clock is the denominator.

Budget-robust by design (a timeout must still yield a metric):
- generated data persists under .bench_data/ and reloads on re-runs;
- the XLA persistent compilation cache (.xla_cache/) makes compiles
  one-time costs across processes;
- results bank incrementally per query and SIGTERM/SIGINT prints the
  final JSON from whatever has completed, pairing device and CPU times
  over the same completed-query set.

value = device power-run total seconds; vs_baseline = cpu_total /
device_total over completed queries (>1 means the TPU engine wins).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

# Scale factors balance signal vs budget: large enough that device
# compute dominates the per-query tunnel RTT floor, small enough that
# the CPU-oracle denominator finishes within the driver budget; data
# (.bench_data/) and XLA executables (.xla_cache/) persist across runs,
# so the driver's timed run skips datagen and compiles
SF_H = float(os.environ.get("BENCH_SF", "0.3"))
SF_DS = float(os.environ.get("BENCH_NDS_SF", "0.1"))
HERE = os.path.dirname(os.path.abspath(__file__))
DATA_ROOT = os.environ.get("BENCH_DATA", os.path.join(HERE, ".bench_data"))
# which legs run (comma list); the NDS-H leg runs first so a budget
# kill still records the historical headline metric
LEGS = os.environ.get("BENCH_LEGS", "nds_h,nds").split(",")

# banked per-query results: (leg, qname) -> {"device_s": .., "cpu_s": ..}
BANK: dict[tuple, dict] = {}
LEG_TOTALS: dict[str, int] = {}  # leg -> queries_total
_done = False


def _leg_line(leg: str, metric: str) -> dict:
    paired = {k: r for k, r in BANK.items()
              if k[0] == leg and "device_s" in r and "cpu_s" in r}
    dev = sum(r["device_s"] for r in paired.values())
    cpu = sum(r["cpu_s"] for r in paired.values())
    return {
        "metric": metric,
        "value": round(dev, 4),
        "unit": "s",
        "vs_baseline": round(cpu / dev, 4) if dev else 0.0,
        "queries_completed": len(paired),
        "queries_total": LEG_TOTALS.get(leg, 0),
    }


def _metric_name(leg: str) -> str:
    return (f"nds_h_sf{SF_H:g}_power_total" if leg == "nds_h"
            else f"nds_sf{SF_DS:g}_power_total")


def _combined_dict() -> dict:
    legs = {}
    dev = cpu = completed = total = 0
    for leg in LEGS:
        line = _leg_line(leg, _metric_name(leg))
        legs[_metric_name(leg)] = line
        dev += line["value"]
        cpu += line["value"] * line["vs_baseline"]
        completed += line["queries_completed"]
        total += line["queries_total"]
    return {
        "metric": "nds+nds_h_power_total",
        "value": round(dev, 4),
        "unit": "s",
        "vs_baseline": round(cpu / dev, 4) if dev else 0.0,
        "queries_completed": completed,
        "queries_total": total,
        "legs": legs,
    }


def _combined_line() -> str:
    return json.dumps(_combined_dict())


def _emit_final() -> None:
    global _done
    if _done:
        return
    _done = True
    print(_combined_line(), flush=True)


def _on_term(signum, frame):
    print(f"[bench] signal {signum}: emitting partial metric "
          f"({len(BANK)} queries banked)", file=sys.stderr, flush=True)
    _emit_final()
    sys.exit(0)


def _load_or_gen(leg: str):
    from nds_tpu.io import table_cache
    from nds_tpu.io.host_table import from_arrays
    if leg == "nds_h":
        from nds_tpu.datagen import tpch as gen
        from nds_tpu.nds_h.schema import get_schemas
        sf = SF_H
    else:
        from nds_tpu.datagen import tpcds as gen
        from nds_tpu.nds.schema import get_schemas
        sf = SF_DS
    schemas = get_schemas()
    data_dir = os.path.join(DATA_ROOT, f"{leg}_sf{sf:g}")
    # legacy layout from earlier rounds (nds_h only, no leg prefix)
    legacy = os.path.join(DATA_ROOT, f"sf{sf:g}")
    if leg == "nds_h" and not os.path.isdir(data_dir) \
            and os.path.isdir(legacy):
        data_dir = legacy
    cached = table_cache.load_tables(data_dir, schemas)
    if cached is not None:
        print(f"[bench] {leg}: loaded SF{sf:g} data from {data_dir}",
              file=sys.stderr, flush=True)
        return cached
    print(f"[bench] {leg}: generating SF{sf:g} data...", file=sys.stderr,
          flush=True)
    tables = {t: from_arrays(t, schemas[t], gen.gen_table(t, sf))
              for t in schemas}
    table_cache.save_tables(data_dir, tables)
    return tables


def _statements(leg: str, qn: int, sql: str) -> list[str]:
    if leg == "nds_h":
        from nds_tpu.nds_h.streams import statements
        return list(statements(qn, sql))
    return [s.strip() for s in sql.split(";") if s.strip()]


def _run_query(session, stmts: list[str]) -> float:
    t0 = time.perf_counter()
    for s in stmts:
        session.sql(s)
    return time.perf_counter() - t0


# -------------------------------------------------- CPU-oracle time bank
#
# The 121-query CPU-oracle denominator costs more wall-clock than the
# device leg itself; re-deriving it every driver run is what pushed
# round 3 past the budget (VERDICT r3 "what's missing" #1). CPU times
# are a property of (suite, SF, query, host) only — the deterministic
# generators make the data identical across runs — so they bank to
# DATA_ROOT and reload. BENCH_CPU=fresh forces re-measurement.

def _cpu_bank_path(leg: str) -> str:
    sf = SF_H if leg == "nds_h" else SF_DS
    return os.path.join(DATA_ROOT, f"cpu_times_{leg}_sf{sf:g}.json")


# ------------------------------------------- device-time bank (stale
# fallback): the remote chip tunnel can be down for hours (round 4 lost
# most of a day to one outage). Completed per-query device times
# persist here; when the device is unreachable at startup the bench
# emits the banked metric labeled "stale_device_times": true instead of
# hanging the driver in jax initialization.

def _dev_bank_path(leg: str) -> str:
    sf = SF_H if leg == "nds_h" else SF_DS
    return os.path.join(DATA_ROOT, f"device_times_{leg}_sf{sf:g}.json")


def _save_dev_bank(leg: str) -> None:
    path = _dev_bank_path(leg)
    # merge with what's on disk: a partial run must refine, never
    # destroy, the last complete run's banked times (the stale
    # fallback's whole value)
    try:
        with open(path) as f:
            times = json.load(f)
    except (OSError, ValueError):
        times = {}
    times.update({str(qn): r["device_s"] for (lg, qn), r in BANK.items()
                  if lg == leg and "device_s" in r})
    with open(path + ".tmp", "w") as f:
        json.dump(times, f)
    os.replace(path + ".tmp", path)


def _device_reachable(timeout_s: int = 120) -> bool:
    """jax.devices() blocks forever on a dead tunnel; probe in a
    subprocess with a hard timeout (same pattern as __graft_entry__)."""
    import subprocess
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout_s)
        return int(proc.stdout.strip().splitlines()[-1]) >= 1
    except Exception:  # noqa: BLE001
        return False


def _emit_stale_from_banks() -> bool:
    """Load banked device+cpu times and emit the combined line with an
    explicit staleness marker. Returns False if no banked device leg
    exists (nothing honest to report)."""
    any_pairs = False
    for leg in LEGS:
        try:
            with open(_dev_bank_path(leg)) as f:
                dev_times = json.load(f)
        except (OSError, ValueError):
            continue
        try:
            with open(_cpu_bank_path(leg)) as f:
                cpu_times = json.load(f).get("times", {})
        except (OSError, ValueError):
            cpu_times = {}
        for qn, ds in dev_times.items():
            if qn in cpu_times:
                BANK[(leg, int(qn))] = {"device_s": ds,
                                        "cpu_s": cpu_times[qn]}
                any_pairs = True
    if not any_pairs:
        return False
    line = _combined_dict()
    line["stale_device_times"] = True
    line["note"] = ("TPU unreachable at bench time; values are the "
                    "last completed real-chip run's banked per-query "
                    "times")
    print(json.dumps(line), flush=True)
    return True


def _load_cpu_bank(leg: str, tables) -> dict:
    if os.environ.get("BENCH_CPU", "auto") == "fresh":
        return {}
    try:
        with open(_cpu_bank_path(leg)) as f:
            bank = json.load(f)
    except (OSError, ValueError):
        return {}
    # fingerprint: banked times are only valid for identical data
    rows = {t: tb.nrows for t, tb in tables.items()}
    if bank.get("rows") != rows:
        return {}
    return bank.get("times", {})


def _save_cpu_bank(leg: str, tables, times: dict) -> None:
    path = _cpu_bank_path(leg)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"rows": {t: tb.nrows for t, tb in tables.items()},
                   "times": times}, f)
    os.replace(tmp, path)


# transient transport failures from the remote-attached chip extend
# beyond compiles (round 3 lost q22 to a BrokenPipeError mid-transfer):
# any failure matching these marks retries instead of failing the query
_TRANSIENT = ("brokenpipe", "unexpected eof", "response body closed",
              "connection", "unavailable", "deadline", "transport",
              "remote_compile", "socket")


def _is_transient(exc: BaseException) -> bool:
    s = f"{type(exc).__name__}: {exc}".lower()
    return any(t in s for t in _TRANSIENT)


def _cleanup_views(session, stmts: list[str]) -> None:
    """Best-effort drop of any views a half-completed statement list
    left behind, so a retry can replay CREATE VIEW statements."""
    for s in stmts:
        if s.lstrip().lower().startswith("drop view"):
            try:
                session.sql(s)
            except Exception:  # noqa: BLE001
                pass


def _run_leg(leg: str) -> None:
    from nds_tpu.engine.device_exec import make_device_factory
    from nds_tpu.engine.session import Session

    if leg == "nds_h":
        from nds_tpu.nds_h import streams
        qids = list(range(1, 23))
        mk = Session.for_nds_h
    else:
        from nds_tpu.nds import streams
        qids = streams.available_templates()
        mk = Session.for_nds
        # budget insurance: the handful of giant-program templates
        # (multi-hour XLA compiles when the persistent cache is cold)
        # run LAST so a budget kill mid-compile still banks the other
        # 95 queries. Pure ordering — every template still runs, and
        # with a warm cache the order is irrelevant.
        defer = {int(x) for x in os.environ.get(
            "BENCH_DEFER", "39,59,67,78").split(",") if x}
        qids = ([q for q in qids if q not in defer]
                + [q for q in qids if q in defer])

    tables = _load_or_gen(leg)
    dev = mk(make_device_factory())
    cpu = mk()
    for t in tables.values():
        dev.register_table(t)
        cpu.register_table(t)

    cpu_bank = _load_cpu_bank(leg, tables)
    if cpu_bank:
        print(f"[bench] {leg}: {len(cpu_bank)} banked cpu-oracle times "
              f"from {_cpu_bank_path(leg)}", file=sys.stderr, flush=True)

    for qn in qids:
        # one broken query must not cost the rest of the run (the
        # reference's --allow_failure mode, `nds/nds_power.py:391-393`)
        try:
            sql = streams.render_query(qn)
            stmts = _statements(leg, qn, sql)
            # untimed warmup: AOT compile + one execution per statement.
            # The remote compile service drops connections under long
            # compiles ("response body closed" / "Unexpected EOF") —
            # transient, so retry PER STATEMENT (retrying the whole
            # list would replay a succeeded CREATE VIEW and turn the
            # transient into a hard 'view already exists')
            for s in stmts:
                for attempt in range(3):
                    try:
                        dev.sql(s)
                        break
                    except Exception as exc:  # noqa: BLE001
                        if attempt == 2 or not _is_transient(exc):
                            raise
                        print(f"[bench] {leg} q{qn}: transient compile "
                              f"error, retrying statement",
                              file=sys.stderr, flush=True)
            # timed run, with transient-transport retry (the whole
            # statement list replays; drops run first so re-created
            # views don't collide)
            for attempt in range(3):
                try:
                    dev_s = _run_query(dev, stmts)
                    break
                except Exception as exc:  # noqa: BLE001
                    if attempt == 2 or not _is_transient(exc):
                        raise
                    print(f"[bench] {leg} q{qn}: transient error in "
                          f"timed run ({type(exc).__name__}), retrying",
                          file=sys.stderr, flush=True)
                    _cleanup_views(dev, stmts)
            BANK.setdefault((leg, qn), {})["device_s"] = dev_s
            _save_dev_bank(leg)
            # engine-side perf accounting (compile/execute/materialize)
            dev_ex = dev._executor_factory(dev.tables)
            tm = dict(dev_ex.last_timings)
            banked = cpu_bank.get(str(qn))
            if banked is not None:
                cpu_s = float(banked)
            else:
                cpu_s = _run_query(cpu, stmts)
                cpu_bank[str(qn)] = cpu_s
                _save_cpu_bank(leg, tables, cpu_bank)
            BANK[(leg, qn)]["cpu_s"] = cpu_s
        except Exception as exc:  # noqa: BLE001
            BANK.pop((leg, qn), None)
            print(f"[bench] {leg} q{qn}: FAILED {type(exc).__name__}: "
                  f"{exc}", file=sys.stderr, flush=True)
            continue
        print(f"[bench] {leg} q{qn}: tpu {dev_s*1000:.0f} ms "
              f"(exec {tm.get('execute_ms', 0):.0f} "
              f"mat {tm.get('materialize_ms', 0):.0f} "
              f"{tm.get('scan_gbps', 0):.1f}GB/s) | "
              f"cpu {cpu_s*1000:.0f} ms"
              f"{' [banked]' if banked is not None else ''}",
              file=sys.stderr, flush=True)
        # the full combined partial (not a leg-scoped line): a hard kill
        # can defer the SIGTERM handler inside XLA C++, so the last
        # printed line must already carry every completed leg
        print(_combined_line(), flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    # totals for EVERY leg up front — and before the (multi-second,
    # kill-prone) TPU init below: a kill at any point must still count
    # every leg's queries in queries_total (else a 22/22 nds_h-only
    # partial reads as a complete 121-query run)
    for leg in LEGS:
        if leg == "nds_h":
            LEG_TOTALS[leg] = 22
        else:
            from nds_tpu.nds import streams as nds_streams
            LEG_TOTALS[leg] = len(nds_streams.available_templates())

    # the probe only matters when a stale emit is possible: without a
    # banked device leg there is nothing to fall back to, and a healthy
    # tunnel shouldn't pay a second serial jax init
    if any(os.path.exists(_dev_bank_path(leg)) for leg in LEGS) \
            and not _device_reachable():
        print("[bench] TPU unreachable (tunnel down) — emitting banked "
              "metric from the last completed real-chip run",
              file=sys.stderr, flush=True)
        if _emit_stale_from_banks():
            return

    from nds_tpu.utils.xla_cache import enable as enable_xla_cache
    cache_dir = enable_xla_cache()
    print(f"[bench] xla cache: {cache_dir}", file=sys.stderr, flush=True)

    import jax
    print(f"[bench] backend: {jax.default_backend()} {jax.devices()}",
          file=sys.stderr, flush=True)

    for leg in LEGS:
        _run_leg(leg)

    _emit_final()


if __name__ == "__main__":
    sys.exit(main())
