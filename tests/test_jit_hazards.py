"""Tier-1 for the recompile & transfer hazard pair: every ndsjit rule
(nds_tpu/analysis/jit_hazards.py) fires on its positive fixture and
stays silent on its negative twin (tests/fixtures/jit_hazards/), the
shared suppression grammar (waive[] / disable=) holds under the ndsjit
marker, and the runtime sanitizer (nds_tpu/analysis/jitsan.py) catches
a SEEDED post-warmup recompile and a hidden ``.item()`` on a private
Sanitizer — the proof the detector would catch the real thing."""

import pathlib

import pytest

from nds_tpu.analysis import jit_hazards

FIXTURES = (pathlib.Path(__file__).parent / "fixtures"
            / "jit_hazards")


def _scan(fixture: str, synth_path: str):
    """Feed one fixture to the scanner under a synthetic nds_tpu path
    so the path-scoped rules apply to it."""
    src = (FIXTURES / fixture).read_text()
    return jit_hazards.scan_sources({synth_path: src})


def _hits(res, rule: str):
    return [v for v in res.violations if v.rule == rule]


class TestRuleFixtures:
    # (rule, positive fixture, negative fixture, synthetic path,
    #  minimum positive findings)
    CASES = [
        ("NDSJ301", "traced_leak_pos.py", "traced_leak_neg.py",
         "nds_tpu/engine/fx.py", 3),
        ("NDSJ302", "blind_capture_pos.py", "blind_capture_neg.py",
         "nds_tpu/engine/fx.py", 1),
        ("NDSJ303", "implicit_transfer_pos.py",
         "implicit_transfer_neg.py", "nds_tpu/engine/fx.py", 3),
        ("NDSJ303", "serve_blocking_pos.py", "serve_blocking_neg.py",
         "nds_tpu/serve/fx.py", 1),
        ("NDSJ304", "weak_literal_pos.py", "weak_literal_neg.py",
         "nds_tpu/engine/fx.py", 1),
    ]

    @pytest.mark.parametrize("rule,pos,neg,path,n", CASES,
                             ids=[f"{c[0]}-{c[1]}" for c in CASES])
    def test_positive_fires(self, rule, pos, neg, path, n):
        res = _scan(pos, path)
        hits = _hits(res, rule)
        assert len(hits) >= n, (
            f"{rule} missed its seeded hazard in {pos}: "
            f"{[str(v) for v in res.violations]}")
        # every seeded line is marked in the fixture with the rule id
        src = (FIXTURES / pos).read_text().splitlines()
        for v in hits:
            assert rule in src[v.line - 1], (
                f"{rule} fired on unmarked line {v.line}: "
                f"{src[v.line - 1]!r}")

    @pytest.mark.parametrize("rule,pos,neg,path,n", CASES,
                             ids=[f"{c[0]}-{c[2]}" for c in CASES])
    def test_negative_silent(self, rule, pos, neg, path, n):
        res = _scan(neg, path)
        assert _hits(res, rule) == [], (
            f"{rule} false-positived on {neg}: "
            f"{[str(v) for v in res.violations]}")

    def test_rules_path_scoped(self):
        # the same hazard text outside the audited trees is ignored
        src = (FIXTURES / "implicit_transfer_pos.py").read_text()
        res = jit_hazards.scan_sources({"nds_tpu/obs/fx.py": src})
        assert _hits(res, "NDSJ303") == []


class TestSuppressionGrammar:
    SRC = ('"""mod."""\n'
           "def run(compiled, bufs):\n"
           "    return compiled(bufs, 512){marker}\n")

    def _scan_src(self, marker: str):
        src = self.SRC.format(marker=marker)
        return jit_hazards.scan_sources({"nds_tpu/engine/fx.py": src})

    def test_unsuppressed_fires(self):
        res = self._scan_src("")
        assert len(_hits(res, "NDSJ304")) == 1

    def test_waive_form(self):
        res = self._scan_src(
            "  # ndsjit: waive[NDSJ304] -- zero-arg probe, one key")
        assert res.violations == [] and res.errors == []
        assert [v.rule for v in res.waived] == ["NDSJ304"]
        assert "probe" in res.waived[0].waiver_note

    def test_waive_without_note_is_error(self):
        res = self._scan_src("  # ndsjit: waive[NDSJ304]")
        assert any(e.rule == "NDSJ300" for e in res.errors)

    def test_disable_form_needs_no_note(self):
        res = self._scan_src("  # ndsjit: disable=NDSJ304")
        assert res.violations == [] and res.errors == []
        assert [v.rule for v in res.waived] == ["NDSJ304"]

    def test_stale_disable_is_error(self):
        src = ('"""mod."""\n'
               "def run(n):\n"
               "    return n + 1  # ndsjit: disable=NDSJ304\n")
        res = jit_hazards.scan_sources({"nds_tpu/engine/fx.py": src})
        assert any(e.rule == "NDSJ300"
                   and "matches no violation" in e.msg
                   for e in res.errors)

    def test_marker_inside_string_literal_ignored(self):
        # a marker spelled in a string (this very test file's idiom)
        # must not parse as a suppression of the embedding file
        src = ('"""mod."""\n'
               "TEXT = '# ndsjit: disable=NDSJ304'\n")
        res = jit_hazards.scan_sources({"nds_tpu/engine/fx.py": src})
        assert res.errors == [] and res.waived == []


class TestJitsanRuntime:
    """The seeded-hazard proof on a PRIVATE sanitizer: a deliberate
    post-warmup recompile and a hidden ``.item()`` must both land in
    the window verdict, and the declared read-back must not."""

    @pytest.fixture()
    def jitsan(self):
        jax = pytest.importorskip("jax")
        del jax
        from nds_tpu.analysis import jitsan as js
        assert js.install(), "interposition failed to install"
        yield js
        # the hooks are process-global: restore for test isolation
        js.uninstall()

    def test_seeded_recompile_and_hidden_item_caught(self, jitsan):
        import jax
        import jax.numpy as jnp

        from nds_tpu.cache import aot as cache_aot

        san = jitsan.Sanitizer(metric=False)
        with jitsan.swapped(san):
            san.arm("test.seeded")
            buf = jnp.arange(8, dtype=jnp.float32)
            # the deliberate post-warmup recompile, through the
            # engine's one funnel — exactly a fingerprint gap's shape
            compiled = cache_aot.lower_and_compile(
                jax.jit(lambda x: x * 2), buf, kind="test_recompile")
            with jitsan.dispatch("test"):
                out = compiled(buf)
            _ = out[0].item()  # the hidden sync
            _ = jax.device_get(out)  # sanctioned twin must NOT flag
            with jitsan.declared("scoped readback"):
                _ = out[1].item()  # declared scope: silent by design
            v = san.disarm()
        assert [c["kind"] for c in v["compiles"]] == ["test_recompile"]
        assert len(v["undeclared_transfers"]) == 1
        assert v["undeclared_transfers"][0]["what"] == ".item()"
        assert v["declared_transfers"] >= 1
        assert v["dispatches"] == 1

    def test_dispatch_guard_rejects_host_buffer(self, jitsan):
        import jax
        import numpy as np

        from nds_tpu.cache import aot as cache_aot

        host = np.ones((4,), dtype=np.float32)
        compiled = cache_aot.lower_and_compile(
            jax.jit(lambda x: x + 1), host, kind="test_guard")
        san = jitsan.Sanitizer(metric=False)
        with jitsan.swapped(san):
            san.arm("test.guard")
            with pytest.raises(Exception, match="[Tt]ransfer"):
                with jitsan.dispatch("test"):
                    compiled(host)  # implicit h2d inside the window
            san.disarm()

    def test_disarmed_is_transparent(self, jitsan):
        import jax.numpy as jnp
        san = jitsan.Sanitizer(metric=False)
        with jitsan.swapped(san):
            buf = jnp.ones((2,), jnp.float32)
            with jitsan.dispatch("noop"):
                _ = float(buf[0])  # disarmed: nothing records
            v = san.snapshot()
        assert v["windows"] == [] and san.undeclared == []

    def test_selftest(self, jitsan):
        assert jitsan.selftest()


def test_static_catalog_covers_documented_rules():
    ids = {r.id for r in jit_hazards.default_rules()}
    assert ids == {"NDSJ301", "NDSJ302", "NDSJ303", "NDSJ304"}
