"""Concurrency auditor + runtime lock-order sanitizer tests.

Three layers, mirroring the subsystem (ISSUE 14):

- per-rule positive/negative fixture pairs for the static auditor
  (nds_tpu/analysis/concurrency.py, NDSR201-204), each reproducing a
  shipped bug class (QueryJournal lock-free readers, the
  request_stall_capture self-deadlock, the flight-dump pid-tmp race)
  and its fixed/waived form;
- runtime sanitizer tests (nds_tpu/analysis/locksan.py): a deliberate
  lock-order inversion the sanitizer must catch, the re-entrant-acquire
  guard, condition-variable round-trips through the wrapper, the
  metrics counter, and the atomic exit report;
- tree-sweep + regression: the repo audits clean modulo waivers
  (tools/ndsraces.py exit 0), the PRE-fix server/journal patterns
  flag, and a thread hammer over the fixed QueryJournal stays
  consistent.
"""

import json
import os
import pathlib
import sys
import threading

import pytest

from nds_tpu.analysis import concurrency, locksan

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

FIX = "nds_tpu/serve/fixture.py"


def _audit(src, enabled=None, path=FIX, extra=None):
    sources = {path: src}
    if extra:
        sources.update(extra)
    return concurrency.audit_sources(sources, enabled=enabled)


def _rules(violations):
    return {v.rule for v in violations}


# ------------------------------------------------ NDSR201 guard inference

GUARDED = """
import threading

class J:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = {}

    def record(self, k, v):
        with self._lock:
            self.state[k] = v

    def done(self, k):
        return self.state.get(k)
"""


def test_ndsr201_unguarded_read_flags():
    res = _audit(GUARDED, enabled={"NDSR201"})
    assert _rules(res.violations) == {"NDSR201"}
    assert "read lock-free in done()" in res.violations[0].msg


def test_ndsr201_mutator_call_reports_once():
    # review regression: an unguarded `self.q.append(v)` is ONE write
    # finding, not a write plus a read re-walked out of the receiver
    src = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.q = []

    def put(self, v):
        with self._lock:
            self.q.append(v)

    def leak(self, v):
        self.q.append(v)
"""
    res = _audit(src, enabled={"NDSR201"})
    assert len(res.violations) == 1
    assert "written lock-free" in res.violations[0].msg


def test_ndsr201_unguarded_write_flags():
    src = GUARDED.replace("return self.state.get(k)",
                          "self.state[k] = None")
    res = _audit(src, enabled={"NDSR201"})
    assert _rules(res.violations) == {"NDSR201"}
    assert "written lock-free" in res.violations[0].msg


def test_ndsr201_locked_access_is_clean():
    src = GUARDED.replace(
        "return self.state.get(k)",
        "with self._lock:\n            return self.state.get(k)")
    assert _audit(src, enabled={"NDSR201"}).violations == []


def test_ndsr201_init_and_locked_suffix_exempt():
    # __init__ publishes before threads exist; *_locked methods declare
    # the caller-holds-the-guard contract
    src = GUARDED.replace("def done(self, k):",
                          "def done_locked(self, k):")
    assert _audit(src, enabled={"NDSR201"}).violations == []


def test_ndsr201_wrong_lock_still_flags():
    src = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self.q = []

    def put(self, v):
        with self._cv:
            self.q.append(v)

    def peek(self):
        with self._lock:
            return len(self.q)
"""
    res = _audit(src, enabled={"NDSR201"})
    assert _rules(res.violations) == {"NDSR201"}
    assert "guarded by _cv" in res.violations[0].msg


def test_ndsr201_waiver_and_note():
    src = GUARDED.replace(
        "        return self.state.get(k)",
        "        # ndsraces: waive[NDSR201] -- snapshot read, torn ok\n"
        "        return self.state.get(k)")
    res = _audit(src, enabled={"NDSR201"})
    assert res.violations == [] and len(res.waived) == 1
    assert res.waived[0].waiver_note == "snapshot read, torn ok"


def test_ndsr201_catches_the_prefix_server_and_journal_bugs():
    # the shapes shipped before this PR: QueryServer mutating a
    # lock-guarded stats dict from the engine thread lock-free, and
    # QueryJournal reading state lock-free while the drain thread
    # writes it — both must flag (the auditor's proof of value)
    src = """
import threading

class Server:
    def __init__(self):
        self._lock = threading.Lock()
        self.stats = {"submitted": 0, "batched": 0}

    def submit(self):
        with self._lock:
            self.stats["submitted"] += 1

    def serve_group(self, group):
        self.stats["batched"] += len(group) - 1
"""
    res = _audit(src, enabled={"NDSR201"})
    assert len(res.violations) == 1
    assert "stats" in res.violations[0].msg
    assert "serve_group" in res.violations[0].msg


# ------------------------------------------------ NDSR202 lock order

def test_ndsr202_self_deadlock_via_call_edge():
    # the request_stall_capture bug: holding self._lock while calling a
    # method that acquires the same non-reentrant lock
    src = """
import threading

class P:
    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0

    def _capture_dir(self):
        with self._lock:
            self._seq += 1
            return self._seq

    def request(self):
        with self._lock:
            return self._capture_dir()
"""
    res = _audit(src, enabled={"NDSR202"})
    assert _rules(res.violations) == {"NDSR202"}
    assert "self-deadlock" in res.violations[0].msg


def test_ndsr202_rlock_reentry_is_clean():
    src = """
import threading

class P:
    def __init__(self):
        self._lock = threading.RLock()

    def inner(self):
        with self._lock:
            return 1

    def outer(self):
        with self._lock:
            return self.inner()
"""
    assert _audit(src, enabled={"NDSR202"}).violations == []


CYCLE = """
import threading

class D:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""


def test_ndsr202_ab_ba_cycle_flags_once():
    res = _audit(CYCLE, enabled={"NDSR202"})
    assert len(res.violations) == 1
    assert "lock-order cycle" in res.violations[0].msg


def test_ndsr202_consistent_order_is_clean():
    src = CYCLE.replace("with self._b:\n            with self._a:",
                        "with self._a:\n            with self._b:")
    assert _audit(src, enabled={"NDSR202"}).violations == []


def test_ndsr202_cycle_across_call_edges():
    src = """
import threading

class D:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def take_b(self):
        with self._b:
            pass

    def take_a(self):
        with self._a:
            pass

    def one(self):
        with self._a:
            self.take_b()

    def two(self):
        with self._b:
            self.take_a()
"""
    res = _audit(src, enabled={"NDSR202"})
    assert len(res.violations) == 1
    assert "lock-order cycle" in res.violations[0].msg


# ------------------------------------------------ NDSR203 signal safety

HANDLER = """
import signal
import threading

_lock = threading.Lock()


def flush():
    with _lock:
        pass


def _on_term(signum, frame):
    flush()


def install():
    prev = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, _on_term)
"""


def test_ndsr203_lock_on_signal_path_flags():
    res = _audit(HANDLER, enabled={"NDSR203"},
                 path="nds_tpu/obs/fixture.py")
    assert _rules(res.violations) == {"NDSR203"}
    assert "signal-handler path" in res.violations[0].msg


def test_ndsr203_boundary_waiver_prunes():
    src = HANDLER.replace(
        "def flush():",
        "# ndsraces: waive[NDSR203] -- bounded: worker thread + join timeout\n"
        "def flush():")
    res = _audit(src, enabled={"NDSR203"},
                 path="nds_tpu/obs/fixture.py")
    assert res.violations == [] and res.errors == []
    assert len(res.waived) == 1
    assert "declared bounded boundary" in res.waived[0].msg


def test_ndsr203_timeoutless_join_flags_and_bounded_is_clean():
    src = """
import signal
import threading


def _on_term(signum, frame):
    t = threading.Thread(target=print)
    t.start()
    t.join()


def install():
    prev = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, _on_term)
"""
    res = _audit(src, enabled={"NDSR203"},
                 path="nds_tpu/obs/fixture.py")
    assert _rules(res.violations) == {"NDSR203"}
    assert "join()" in res.violations[0].msg
    bounded = src.replace("t.join()", "t.join(timeout=1.0)")
    assert _audit(bounded, enabled={"NDSR203"},
                  path="nds_tpu/obs/fixture.py").violations == []


def test_ndsr203_locks_outside_signal_paths_dont_flag():
    src = """
import threading

_lock = threading.Lock()


def flush():
    with _lock:
        pass
"""
    assert _audit(src, enabled={"NDSR203"},
                  path="nds_tpu/obs/fixture.py").violations == []


# --------------------------------------- NDSR204 thread-shared mutation

SNAPSHOTTER = """
import threading

class Snap:
    def __init__(self):
        self._warned = False
        self._thread = None

    def write_once(self):
        self._warned = True

    def _loop(self):
        self.write_once()

    def start(self):
        self._thread = threading.Thread(target=self._loop)
        self._thread.start()

    def stop(self):
        self.write_once()
"""


def test_ndsr204_thread_shared_mutation_flags():
    res = _audit(SNAPSHOTTER, enabled={"NDSR204"})
    assert _rules(res.violations) == {"NDSR204"}
    assert "_warned" in res.violations[0].msg


def test_ndsr204_guarded_version_is_clean():
    src = SNAPSHOTTER.replace(
        "self._warned = False",
        "self._warned = False\n        self._lock = threading.Lock()"
    ).replace(
        "    def write_once(self):\n        self._warned = True",
        "    def write_once(self):\n"
        "        with self._lock:\n            self._warned = True")
    assert _audit(src, enabled={"NDSR204"}).violations == []


def test_ndsr204_pid_only_tmp_name_flags():
    src = """
import os
import threading


def write(path, doc):
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(doc)
    os.replace(tmp, path)
"""
    res = _audit(src, enabled={"NDSR204"})
    assert _rules(res.violations) == {"NDSR204"}
    assert "get_ident" in res.violations[0].msg
    fixed = src.replace(
        '{os.getpid()}.tmp', '{os.getpid()}.{threading.get_ident()}.tmp')
    assert _audit(fixed, enabled={"NDSR204"}).violations == []


def test_ndsr204_tmp_rule_scoped_to_threading_modules():
    # a single-threaded writer (cache/store, analyze) is out of scope:
    # pid-unique is all cross-PROCESS atomicity needs
    src = """
import os


def write(path, doc):
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(doc)
    os.replace(tmp, path)
"""
    assert _audit(src, enabled={"NDSR204"}).violations == []


# ----------------------------------------------------- waiver semantics

def test_waiver_requires_justification_and_use():
    src = GUARDED.replace(
        "        return self.state.get(k)",
        "        # ndsraces: waive[NDSR201]\n"
        "        return self.state.get(k)")
    res = _audit(src, enabled={"NDSR201"})
    assert any(v.rule == "NDSR200" for v in res.errors)
    assert _rules(res.violations) == {"NDSR201"}
    stale = "def f(a):\n    # ndsraces: waive[NDSR201] -- nothing\n    return a\n"
    res = _audit(stale)
    assert any("matches no violation" in v.msg for v in res.errors)


# ------------------------------------------------------ runtime locksan

def test_locksan_catches_seeded_inversion():
    g = locksan.OrderGraph(metric=False)
    a = locksan.SanLock("fix.A", g)
    b = locksan.SanLock("fix.B", g)
    with a:
        with b:
            pass
    assert g.inversion_count() == 0
    with b:
        with a:
            pass
    assert g.inversion_count() == 1
    inv = g.snapshot()["inversions"][0]
    assert sorted(inv["cycle"]) == ["fix.A", "fix.B"]
    assert inv["stack"] and inv["prior_stack"]


def test_locksan_consistent_order_stays_clean():
    g = locksan.OrderGraph(metric=False)
    a = locksan.SanLock("c.A", g)
    b = locksan.SanLock("c.B", g)
    for _ in range(3):
        with a:
            with b:
                pass
    assert g.inversion_count() == 0
    assert g.snapshot()["edges"]["c.A -> c.B"]["count"] == 3


def test_locksan_reentrant_acquire_raises_instead_of_deadlocking():
    g = locksan.OrderGraph(metric=False)
    a = locksan.SanLock("r.A", g)
    with a:
        with pytest.raises(RuntimeError, match="re-entrant"):
            a.acquire()
    assert g.inversion_count() == 1
    # non-blocking probes never false-positive (Condition._is_owned)
    with a:
        assert a.acquire(blocking=False) is False
    # two INSTANCES sharing one name are distinct objects: no trip
    a2 = locksan.SanLock("r.A", g)
    with a:
        with a2:
            pass


def test_locksan_rlock_recursion_is_legal():
    g = locksan.OrderGraph(metric=False)
    r = locksan.SanRLock("r.R", g)
    with r:
        with r:
            pass
    assert g.inversion_count() == 0


def test_locksan_rlock_reacquire_records_no_false_inversion():
    # review regression: a reentrant re-acquire can never block, so
    # holding R -> X and then re-entering R under X must NOT record an
    # X -> R edge (which would close a bogus R/X "cycle")
    g = locksan.OrderGraph(metric=False)
    r = locksan.SanRLock("f.R", g)
    x = locksan.SanLock("f.X", g)
    with r:
        with x:
            with r:
                pass
    assert g.inversion_count() == 0
    assert "f.X -> f.R" not in g.snapshot()["edges"]


def test_locksan_condition_keeps_default_reentrancy():
    # threading.Condition()'s default lock is an RLock; the sanitized
    # primitive must keep the same semantics, so re-entering the cv is
    # legal (and wait() under recursion fully releases + restores)
    cv = locksan.condition("f.cv2")
    with cv:
        with cv:
            pass
    hits = []

    def notifier():
        with cv:
            hits.append("go")
            cv.notify()

    with cv:
        with cv:
            t = threading.Thread(target=notifier)
            t.start()
            while not hits:
                cv.wait(timeout=2.0)
            t.join(timeout=5.0)
    assert hits == ["go"]


def test_locksan_condition_roundtrip_through_wrapper():
    assert locksan.enabled(), "conftest must set NDS_TPU_LOCKSAN=1"
    cv = locksan.condition("fix.cv")
    hits = []

    def waiter():
        with cv:
            while not hits:
                cv.wait(timeout=2.0)
            hits.append("seen")

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.05)
    with cv:
        hits.append("go")
        cv.notify()
    t.join(timeout=5.0)
    assert "seen" in hits


def test_locksan_metric_and_report(tmp_path):
    before = locksan.inversion_count()
    from nds_tpu.obs import metrics as obs_metrics
    c0 = obs_metrics.counter("lock_order_inversions_total").value
    a = locksan.SanLock("m.A", locksan.graph())
    b = locksan.SanLock("m.B", locksan.graph())
    try:
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert locksan.inversion_count() == before + 1
        assert obs_metrics.counter(
            "lock_order_inversions_total").value == c0 + 1
        path = locksan.write_report(str(tmp_path / "locksan.json"))
        doc = json.loads((tmp_path / "locksan.json").read_text())
        assert doc["inversions"]
        assert not list(tmp_path.glob("*.tmp"))  # atomic, tmp renamed
        assert path.endswith("locksan.json")
    finally:
        locksan.reset()  # seeded inversions must not leak past the test


def test_locksan_selftest_proves_detector():
    assert locksan.selftest()


def test_locksan_disabled_returns_plain_primitives(monkeypatch):
    monkeypatch.setenv(locksan.ENV, "0")
    assert not locksan.enabled()
    assert not isinstance(locksan.lock("x"), locksan.SanLock)
    assert not isinstance(locksan.condition("x").__enter__(),
                          locksan.SanLock) or True
    monkeypatch.setenv(locksan.ENV, "1")
    assert isinstance(locksan.lock("x"), locksan.SanLock)


# ------------------------------------------------- tree sweep + hammer

def test_tree_audits_clean_modulo_waivers(capsys):
    import ndsraces
    assert ndsraces.run(REPO) == 0
    out = capsys.readouterr().out
    assert "OK: 0 violation(s)" in out


def test_waiver_report_covers_both_tools(capsys):
    import ndsraces
    assert ndsraces.waiver_report(REPO) == 0
    out = capsys.readouterr().out
    assert "ndslint:" in out and "ndsraces:" in out
    assert "0 stale waiver(s)" in out


def test_query_journal_thread_hammer(tmp_path):
    # regression for the lock-free readout fix: reader threads hammer
    # done()/completed()/starts() while writers record and the "drain
    # thread" stamps aborts — no exception, consistent final state
    from nds_tpu.resilience.journal import QueryJournal
    j = QueryJournal(str(tmp_path / "q.json"), phase="hammer")
    errors = []

    def writer():
        try:
            for i in range(40):
                j.start(f"q{i}")
                j.record(f"q{i}", 1.0, "Completed", f"d{i}")
        except Exception as exc:  # noqa: BLE001 - the assertion
            errors.append(exc)

    def aborter():
        try:
            for i in range(40):
                j.mark_aborted(f"q{i}", "drain-deadline")
        except Exception as exc:  # noqa: BLE001 - the assertion
            errors.append(exc)

    def reader():
        try:
            for i in range(40):
                j.done(f"q{i}")
                j.completed()
                j.starts(f"q{i}")
                j.entry(f"q{i}")
        except Exception as exc:  # noqa: BLE001 - the assertion
            errors.append(exc)

    threads = [threading.Thread(target=f)
               for f in (writer, aborter, reader, reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert errors == []
    assert len(j.completed()) == 40
    # every recorded query is done (completion wins over a racing
    # abort stamp, by design), and the on-disk journal round-trips
    j2 = QueryJournal(str(tmp_path / "q.json"), phase="hammer")
    assert j2.load()
    assert len(j2.completed()) == 40


def test_write_json_atomic_thread_unique_tmp(tmp_path):
    # the NDS109 dogfood fix: concurrent same-path writers from two
    # threads of one pid never truncate each other — the file is
    # always complete, parseable JSON
    from nds_tpu.io.integrity import write_json_atomic
    path = str(tmp_path / "doc.json")
    errors = []

    def spin(tag):
        try:
            for i in range(60):
                write_json_atomic(path, {"tag": tag, "i": i,
                                         "pad": "x" * 4096})
        except Exception as exc:  # noqa: BLE001 - the assertion
            errors.append(exc)

    threads = [threading.Thread(target=spin, args=(t,))
               for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert errors == []
    doc = json.loads(open(path).read())
    assert doc["tag"] in ("a", "b") and len(doc["pad"]) == 4096
    assert not list(tmp_path.glob("*.tmp"))


def test_ndsraces_in_tier1_static_checks():
    # the gate wiring: static_checks carries both new sections
    text = (REPO / "tools" / "static_checks.py").read_text()
    assert '"ndsraces"' in text and '"locksan"' in text
