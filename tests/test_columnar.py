"""Parity + unit tests for the compressed device-resident columnar
store (nds_tpu/columnar/).

The differential contract mirrors the repo's kernel/parity suites:
every encoding family (bitpack / rle / dict-code packing / packed null
masks) x every placement (device / chunked / cpu / sharded
virtual-mesh) must produce results IDENTICAL to the unencoded run —
including null join keys, empty tables, all-rows-filtered results, and
dictionary-miss literals arriving through the PR 11 parameterized-plan
binder. A fixed-seed fuzz tier re-rolls the table content.

Unit tier: encode/decode round-trips per encoding against numpy,
EncSpec JSON round-trip, malformed-spec rejection through the plan
verifier, auto-mode selection behavior, the encoded-width cost
estimate, the configurable dict-union cap, and the NDS116
early-materialization lint rule fixtures.
"""

import numpy as np
import pytest

from nds_tpu import columnar
from nds_tpu.columnar import device as cdev
from nds_tpu.columnar import encodings as E
from nds_tpu.engine.chunked_exec import make_chunked_factory
from nds_tpu.engine.cpu_exec import CpuExecutor
from nds_tpu.engine.device_exec import DeviceExecutor, _Trace, \
    make_device_factory
from nds_tpu.engine.session import Session
from nds_tpu.engine.types import DATE, INT32, INT64, Schema, varchar
from nds_tpu.io.host_table import from_arrays
from nds_tpu.sql.planner import CatalogInfo

NF = 3000
ND = 40


@pytest.fixture(autouse=True)
def _reset_columnar():
    yield
    columnar.set_mode(None)
    columnar.set_dict_union_cap(None)


def _catalog():
    fact = Schema.of(
        ("f_id", INT64, False),        # wide int64 (bits=32 downcast)
        ("f_dim", INT32, True),        # narrow + NULLs (bitpack+mask)
        ("f_date", DATE, False),       # sorted (rle)
        ("f_tag", varchar(8), True),   # dict codes + NULLs
        ("f_qty", INT64, False))       # narrow int64
    dim = Schema.of(("d_id", INT32, False),
                    ("d_name", varchar(10), False))
    empty = Schema.of(("e_id", INT32, False))
    return CatalogInfo({"fact": fact, "dim": dim, "empty": empty},
                       {"dim": ["d_id"], "fact": ["f_id"]},
                       {"fact": NF, "dim": ND, "empty": 0})


def _tables(seed=20260804):
    rng = np.random.default_rng(seed)
    cat = _catalog()
    tags = np.array(["red", "green", "blue", "cyan"], dtype=object)
    names = np.array([f"name{i % 7}" for i in range(ND)], dtype=object)
    fact = {
        "f_id": (np.arange(NF, dtype=np.int64) + 5_000_000_000),
        "f_dim": rng.integers(0, ND, NF).astype(np.int32),
        "f_dim#null": rng.random(NF) > 0.15,
        "f_date": np.sort(rng.integers(10_000, 10_040, NF))
        .astype(np.int32),
        "f_tag": tags[rng.integers(0, len(tags), NF)],
        "f_tag#null": rng.random(NF) > 0.1,
        "f_qty": rng.integers(0, 500, NF).astype(np.int64),
    }
    dim = {"d_id": np.arange(ND, dtype=np.int32), "d_name": names}
    empty = {"e_id": np.zeros(0, dtype=np.int32)}
    schemas = cat.schemas
    return cat, {
        "fact": from_arrays("fact", schemas["fact"], fact),
        "dim": from_arrays("dim", schemas["dim"], dim),
        "empty": from_arrays("empty", schemas["empty"], empty),
    }


QUERIES = [
    # every encoding at once: rle date filter, packed dim key join,
    # dict-coded group key, packed-mask nulls
    ("select d_name, count(*) as cnt, sum(f_qty) as q from fact "
     "join dim on f_dim = d_id where f_date >= 10010 "
     "group by d_name order by d_name"),
    # dict codes end-to-end: string predicate + string group key
    ("select f_tag, count(*) as cnt from fact "
     "where f_tag <> 'green' and f_tag like 'b%' "
     "group by f_tag order by f_tag"),
    # IN list over packed ints + order by the wide int64
    ("select f_id, f_qty from fact where f_qty in (1, 2, 3) "
     "and f_date < 10005 order by f_id"),
    # all rows filtered out (empty result through encoded scans)
    "select f_id from fact where f_qty < 0 order by f_id",
    # empty TABLE scan under an active mode
    "select count(*) as c from empty",
]


def _session(cat, tables, factory, parameterize=None):
    s = Session(cat, factory, parameterize=parameterize)
    for t in tables.values():
        # fresh column objects per session: the spec memo must never
        # leak one mode's choice into another session's upload
        s.register_table(t)
    return s


def _run_all(cat, tables, factory_fn, queries, mode):
    columnar.set_mode(mode)
    try:
        s = _session(cat, tables, factory_fn())
        return [s.sql(q).to_pandas() for q in queries]
    finally:
        columnar.set_mode(None)


def _assert_same(base, got, label):
    for i, (b, g) in enumerate(zip(base, got)):
        assert b.equals(g), (
            f"{label}: query #{i} differs\nbase:\n{b}\ngot:\n{g}")


# ------------------------------------------------------ parity matrix

MODES = ("auto", "dict", "bitpack", "rle")


def test_device_parity_every_mode():
    cat, tables = _tables()
    base = _run_all(cat, tables, make_device_factory, QUERIES, "off")
    for mode in MODES:
        got = _run_all(cat, tables, make_device_factory, QUERIES,
                       mode)
        _assert_same(base, got, f"device/{mode}")


def test_device_bytes_actually_drop():
    cat, tables = _tables()
    columnar.set_mode("off")
    try:
        s = _session(cat, tables, make_device_factory())
        s.sql(QUERIES[0])
        t_off = dict(s._executor_factory(s.tables).last_timings)
    finally:
        columnar.set_mode(None)
    columnar.set_mode("auto")
    try:
        s = _session(cat, tables, make_device_factory())
        s.sql(QUERIES[0])
        t_on = dict(s._executor_factory(s.tables).last_timings)
    finally:
        columnar.set_mode(None)
    assert t_on["bytes_scanned"] < t_off["bytes_scanned"] / 2
    assert t_on["compression_ratio"] > 2.0
    assert t_on["bytes_scanned_raw"] == pytest.approx(
        t_off["bytes_scanned"])
    # off preserves byte-identical pre-columnar accounting
    assert "compression_ratio" not in t_off
    assert "bytes_scanned_raw" not in t_off


def test_chunked_parity():
    cat, tables = _tables()

    def factory():
        return make_chunked_factory(stream_bytes=1 << 12,
                                    chunk_rows=1 << 10)

    queries = QUERIES + [
        # partial-agg shape: full-scan aggregate over the streamed
        # fact (the chunk-swap path that must upload raw)
        "select count(*) as c, sum(f_qty) as s, avg(f_qty) as a "
        "from fact",
    ]
    base = _run_all(cat, tables, factory, queries, "off")
    for mode in ("auto", "bitpack"):
        got = _run_all(cat, tables, factory, queries, mode)
        _assert_same(base, got, f"chunked/{mode}")


def test_cpu_parity():
    cat, tables = _tables()

    def factory():
        return lambda t: CpuExecutor(t)

    base = _run_all(cat, tables, factory, QUERIES, "off")
    got = _run_all(cat, tables, factory, QUERIES, "auto")
    _assert_same(base, got, "cpu/auto")


def test_sharded_virtual_mesh_parity():
    from nds_tpu.parallel.dist_exec import DistributedExecutor
    cat, tables = _tables()

    def factory():
        return lambda t: DistributedExecutor(t, n_devices=8)

    qs = QUERIES[:2]
    base = _run_all(cat, tables, factory, qs, "off")
    got = _run_all(cat, tables, factory, qs, "auto")
    _assert_same(base, got, "sharded/auto")


@pytest.mark.parametrize("seed", [1, 2])
def test_device_parity_fuzz(seed):
    cat, tables = _tables(seed=seed * 7919)
    qs = QUERIES[:3]
    base = _run_all(cat, tables, make_device_factory, qs, "off")
    got = _run_all(cat, tables, make_device_factory, qs, "auto")
    _assert_same(base, got, f"fuzz/{seed}")


def test_dictionary_miss_literal_via_param_binder():
    """PR 11 interaction: a parameterized plan whose string literal
    MISSES the dictionary must stay correct over encoded buffers, and
    literal variants must keep sharing one compiled program."""
    cat, tables = _tables()
    sql_t = ("select count(*) as c from fact where f_tag = '%s'")
    lits = ["red", "zzz_not_in_dictionary", "blue"]
    columnar.set_mode("off")
    try:
        s = _session(cat, tables, make_device_factory(),
                     parameterize=True)
        base = [s.sql(sql_t % v).to_pandas() for v in lits]
    finally:
        columnar.set_mode(None)
    columnar.set_mode("auto")
    try:
        s = _session(cat, tables, make_device_factory(),
                     parameterize=True)
        got = [s.sql(sql_t % v).to_pandas() for v in lits]
        ex = s._executor_factory(s.tables)
        # all three literal variants landed on ONE compiled entry
        qkeys = [k for k in ex._compiled
                 if not (isinstance(k, tuple)
                         and k and k[0] == "__compact__")]
        assert len(qkeys) == 1, qkeys
    finally:
        columnar.set_mode(None)
    _assert_same(base, got, "param-binder")


# ------------------------------------------------------------ unit tier

def _decode_np(spec, bufs_np, key="k"):
    import jax.numpy as jnp
    bufs = {key + sfx: jnp.asarray(v) for sfx, v in bufs_np.items()}
    arr, valid = cdev.decode(
        spec, {key + sfx: bufs[key + sfx] for sfx in ("", "#v", "#x")
               if key + sfx in bufs}, key)
    return (np.asarray(arr),
            None if valid is None else np.asarray(valid))


def test_bitpack_roundtrip_all_widths():
    rng = np.random.default_rng(3)
    columnar.set_mode("auto")
    for span, dtype in ((1, np.int32), (13, np.int32),
                        (250, np.int16), (60_000, np.int32),
                        (2**30, np.int64)):
        vals = rng.integers(-span // 2, span // 2 + 1, 400) \
            .astype(dtype)
        mask = rng.random(400) > 0.2
        spec = E.plan_values(vals, mask)
        assert spec is not None and spec.kind == "bitpack", (span,
                                                            spec)
        arr, valid = _decode_np(spec, E.encode_values(spec, vals,
                                                      mask))
        assert arr.dtype == vals.dtype
        np.testing.assert_array_equal(arr[mask], vals[mask])
        np.testing.assert_array_equal(valid, mask)
        assert E.encoded_nbytes(spec) < E.raw_nbytes(vals, mask)


def test_rle_roundtrip_and_selection():
    rng = np.random.default_rng(4)
    columnar.set_mode("rle")
    sv = np.sort(rng.integers(0, 30, 5000)).astype(np.int64)
    spec = E.plan_values(sv, None)
    assert spec.kind == "rle" and spec.runs <= 30
    arr, valid = _decode_np(spec, E.encode_values(spec, sv))
    np.testing.assert_array_equal(arr, sv)
    assert valid is None
    # high-cardinality column refuses RLE even when forced
    noisy = rng.integers(0, 1 << 40, 5000).astype(np.int64)
    assert E.plan_values(noisy, None) is None
    # null-masked columns never RLE
    assert E.plan_values(sv, rng.random(5000) > 0.5) is None
    # floats never RLE: value-equality runs would splice -0.0/+0.0
    # into one run and the decode would flip signbits vs raw
    fz = np.concatenate([np.full(500, -0.0), np.full(500, 0.0),
                         np.full(500, 2.5)])
    assert E.plan_values(fz, None) is None
    from nds_tpu.analysis.plan_verify import PlanVerifyError
    with pytest.raises(PlanVerifyError):
        E.encode_values(E.EncSpec("rle", 1500, "float64", runs=2), fz)


def test_dict_mode_touches_only_string_columns():
    """Forced ``dict`` mode is a differential-debugging isolate: it
    must leave every non-string column's buffer set untouched —
    including the null-mask packing."""
    rng = np.random.default_rng(11)
    ints = rng.integers(0, 50, 2000).astype(np.int64)
    mask = rng.random(2000) > 0.2
    assert E.plan_values(ints, mask, mode="dict",
                         is_string=False) is None
    # ...while a dictionary-code column still packs codes AND mask
    spec = E.plan_values(ints.astype(np.int32), mask, mode="dict",
                         is_string=True)
    assert spec is not None and spec.kind == "bitpack" \
        and spec.mask_packed


def test_mask_only_packing():
    rng = np.random.default_rng(5)
    columnar.set_mode("auto")
    vals = rng.standard_normal(2000)  # floats: values stay raw
    mask = rng.random(2000) > 0.3
    spec = E.plan_values(vals, mask)
    assert spec is not None and spec.kind == "raw" and spec.mask_packed
    arr, valid = _decode_np(spec, E.encode_values(spec, vals, mask))
    np.testing.assert_array_equal(arr, vals)
    np.testing.assert_array_equal(valid, mask)


def test_spec_json_roundtrip():
    spec = E.EncSpec("bitpack", 100, "int32", bits=8, lo=-5,
                     mask_packed=True)
    assert E.spec_from_json(E.spec_to_json(spec)) == spec
    assert E.spec_from_json({"kind": "nope", "rows": 1,
                             "dtype": "int32"}) is None
    assert E.spec_from_json({"bogus": True}) is None


def test_malformed_specs_rejected_by_verifier():
    from nds_tpu.analysis.plan_verify import PlanVerifyError
    vals = np.arange(100, dtype=np.int64) + 1000
    # range overflow: bits too narrow for the live values
    with pytest.raises(PlanVerifyError):
        E.encode_values(E.EncSpec("bitpack", 100, "int64", bits=4,
                                  lo=1000), vals)
    # row-count drift
    with pytest.raises(PlanVerifyError):
        E.encode_values(E.EncSpec("bitpack", 99, "int64", bits=8,
                                  lo=1000), vals)
    # dtype drift (encoded-dtype propagation invariant)
    with pytest.raises(PlanVerifyError):
        E.encode_values(E.EncSpec("bitpack", 100, "int32", bits=8,
                                  lo=1000), vals)
    # rle over a null-masked column
    with pytest.raises(PlanVerifyError):
        E.encode_values(E.EncSpec("rle", 100, "int64", runs=1),
                        vals, np.ones(100, dtype=bool))
    # wrong run count
    with pytest.raises(PlanVerifyError):
        E.encode_values(E.EncSpec("rle", 100, "int64", runs=3), vals)


def test_estimate_plan_uses_encoded_widths():
    from nds_tpu.analysis import plan_verify
    cat, tables = _tables()
    columnar.set_mode("off")
    try:
        s = _session(cat, tables, make_device_factory())
        planned = s.plan(QUERIES[0])
        est_off = plan_verify.estimate_plan(planned, tables=tables)
    finally:
        columnar.set_mode(None)
    columnar.set_mode("auto")
    try:
        est_on = plan_verify.estimate_plan(planned, tables=tables)
    finally:
        columnar.set_mode(None)
    assert est_on.bytes < est_off.bytes / 2, (est_on, est_off)
    # encoded=False forces raw widths even under an active mode: the
    # scheduler passes it when costing the sharded placement, which
    # uploads raw (COLUMNAR_UPLOAD=False) — encoded math there would
    # under-count residency by the compression ratio
    columnar.set_mode("auto")
    try:
        est_raw = plan_verify.estimate_plan(planned, tables=tables,
                                            encoded=False)
    finally:
        columnar.set_mode(None)
    assert est_raw.bytes == est_off.bytes
    # catalog-only estimates are mode-independent
    est_cat = plan_verify.estimate_plan(planned, catalog=cat)
    assert est_cat.bytes == plan_verify.estimate_plan(
        planned, catalog=cat).bytes


def test_dict_union_cap_configurable():
    columnar.set_dict_union_cap(2)
    ex = DeviceExecutor({})
    tr = _Trace(ex, {})
    dicts = [np.array([f"s{i}a", f"s{i}b"], dtype=object)
             for i in range(5)]
    for i in range(4):
        tr._dict_union(dicts[i], dicts[i + 1])
    assert len(ex._union_cache) <= 2
    columnar.set_dict_union_cap(None)
    assert columnar.dict_union_cap() == 256
    # cap<=0 ("disable the memo") floors at 1 instead of popping from
    # an empty dict mid-query
    columnar.set_dict_union_cap(0)
    assert columnar.dict_union_cap() == 1
    ex2 = DeviceExecutor({})
    tr2 = _Trace(ex2, {})
    tr2._dict_union(dicts[0], dicts[1])
    tr2._dict_union(dicts[1], dicts[2])
    assert len(ex2._union_cache) == 1


def test_plan_padded_ignores_pad_zeros():
    """Reduced scan views pad survivors with zeros; the encoding plan
    must derive from the LIVE prefix or the pads drag the bitpack
    bounds to [0, max] and forfeit the shrink on the hot
    filtered-scan path."""
    rng = np.random.default_rng(9)
    columnar.set_mode("auto")
    live = rng.integers(2_450_000, 2_452_000, 1000).astype(np.int32)
    padded = np.concatenate([live, np.zeros(24, dtype=np.int32)])
    # planning over the padded array sees span ~2.45M on int32: no fit
    assert E.plan_values(padded, None) is None
    spec = E.plan_padded(padded, None, 1000)
    assert spec is not None and spec.kind == "bitpack"
    assert spec.rows == len(padded) and spec.lo >= 2_450_000
    arr, _ = _decode_np(spec, E.encode_values(spec, padded, None,
                                              nrows=1000))
    np.testing.assert_array_equal(arr[:1000], live)
    # RLE over a padded sorted column: runs derive from the live
    # prefix, the decode extends the last run over the pad tail
    sv = np.sort(rng.integers(100, 130, 2000)).astype(np.int64)
    spad = np.concatenate([sv, np.zeros(48, dtype=np.int64)])
    rspec = E.plan_padded(spad, None, 2000)
    assert rspec is not None and rspec.kind == "rle"
    arr2, _ = _decode_np(rspec, E.encode_values(rspec, spad, None,
                                                nrows=2000))
    np.testing.assert_array_equal(arr2[:2000], sv)
    assert arr2[-1] == sv[-1]  # pad rows read the last run, not 0


def test_configure_from_and_env(monkeypatch):
    from nds_tpu.utils.config import EngineConfig
    columnar.configure_from(EngineConfig(overrides={
        "columnar.encode": "auto", "columnar.dict_union_cap": "17"}))
    assert columnar.mode() == "auto"
    assert columnar.dict_union_cap() == 17
    # a config WITHOUT the keys resets to env resolution
    columnar.configure_from(EngineConfig())
    monkeypatch.setenv("NDS_TPU_COLUMNAR", "bitpack")
    assert columnar.mode() == "bitpack"
    monkeypatch.setenv("NDS_TPU_COLUMNAR", "not-a-mode")
    assert columnar.mode() == "off"  # typos degrade, never crash
    with pytest.raises(ValueError):
        columnar.set_mode("not-a-mode")


def test_fingerprint_token_changes_cache_key():
    from nds_tpu.cache.fingerprint import fingerprint
    cat, tables = _tables()
    columnar.set_mode("off")
    s = _session(cat, tables, make_device_factory())
    planned = s.plan(QUERIES[0])
    fp_off = fingerprint(planned, tables, kind="DeviceExecutor")
    columnar.set_mode("auto")
    fp_on = fingerprint(planned, tables, kind="DeviceExecutor")
    columnar.set_mode(None)
    assert fp_off != fp_on


def test_nds116_early_materialization_rule():
    from nds_tpu.analysis.lint_rules import lint_sources
    src_bad = (
        '"""mod."""\n'
        "def _run_scan(col):\n"
        "    vals = col.decode()\n"
        "    s = col.dictionary[idx]\n"
        "    return vals, s\n")
    res = lint_sources({"nds_tpu/engine/x.py": src_bad},
                       enabled={"NDS116"})
    assert len(res.violations) == 2
    # the result compactor is THE materialization point: exempt
    src_ok = (
        '"""mod."""\n'
        "def _materialize(col):\n"
        "    return col.decode()\n")
    res = lint_sources({"nds_tpu/engine/x.py": src_ok},
                       enabled={"NDS116"})
    assert not res.violations
    # the CPU oracle materializes by contract: exempt by path
    res = lint_sources({"nds_tpu/engine/cpu_exec.py": src_bad},
                       enabled={"NDS116"})
    assert not res.violations
    # waivers work like every other rule
    src_waived = (
        '"""mod."""\n'
        "def plan_side(col):\n"
        "    # ndslint: waive[NDS116] -- host planning\n"
        "    return col.decode()\n")
    res = lint_sources({"nds_tpu/engine/x.py": src_waived},
                       enabled={"NDS116"})
    assert not res.violations and len(res.waived) == 1


def test_table_compression_report():
    cat, tables = _tables()
    columnar.set_mode("auto")
    try:
        comp = columnar.table_compression(tables["fact"])
        assert comp["ratio"] > 2.0
        assert comp["encoded_bytes"] < comp["raw_bytes"]
        # empty tables report cleanly
        comp0 = columnar.table_compression(tables["empty"])
        assert comp0["ratio"] == 1.0
    finally:
        columnar.set_mode(None)


def test_diff_gates_on_bytes_regressions():
    from nds_tpu.obs.analyze import bytes_changes
    base = {"q1": {"bytes_scanned": 1e6}, "q2": {"bytes_scanned": 8e6},
            "q3": {}}
    cur = {"q1": {"bytes_scanned": 1e6},
           "q2": {"bytes_scanned": 32e6},   # 4x growth: regression
           "q3": {"bytes_scanned": 5e5}}    # feature boundary: flag only
    ch = {e["query"]: e for e in bytes_changes(base, cur)}
    assert "q1" not in ch
    assert ch["q2"].get("regressed") is True
    assert "regressed" not in ch["q3"]
    # sub-floor wobble is noise even at a high relative delta
    small = bytes_changes({"q": {"bytes_scanned": 1000}},
                          {"q": {"bytes_scanned": 5000}})
    assert "regressed" not in small[0]
