"""Fleet observability tests (nds_tpu/obs/fleet.py + obs/profile.py):
the clock-alignment handshake + per-rank shard merge on a REAL
2-process world with artificially skewed clocks, the flight-recorder
ring/dump schema round-trip, the watchdog stall-hook registry, the
profiler trigger policy, straggler attribution in the analyzer, the
deterministic Chrome-export identities, and the exchange skew gauge."""

import json
import os
import subprocess
import sys
import time

import pytest

from nds_tpu.obs import analyze, fleet
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.obs import trace as obs_trace
from nds_tpu.obs.profile import ProfilePolicy, Profiler
from nds_tpu.resilience import watchdog
from nds_tpu.utils.config import EngineConfig
from tools.check_trace_schema import (
    validate_flight, validate_flight_file, validate_summary,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------- profiler triggers

class TestProfilePolicy:
    def test_explicit_query_list(self):
        p = ProfilePolicy("/tmp/x", "query21,query72")
        assert p.trigger_for("query21", None) == "query"
        assert p.trigger_for("query72", 5.0) == "query"
        assert p.trigger_for("query1", None) is None

    def test_all_and_stall_modes(self):
        assert ProfilePolicy("/t", "all").trigger_for("q", None) \
            == "query"
        assert ProfilePolicy("/t", "stall").trigger_for("q", 1e9) \
            is None

    def test_slow_trigger_needs_prior_run(self):
        p = ProfilePolicy("/t", "slow", slow_query_ms=500)
        assert p.trigger_for("q", None) is None       # no history yet
        assert p.trigger_for("q", 400.0) is None      # under threshold
        assert p.trigger_for("q", 501.0) == "slow"

    def test_from_config_keys(self):
        cfg = EngineConfig(overrides={
            "engine.profile.dir": "/tmp/prof",
            "engine.profile.mode": "slow",
            "engine.profile.slow_query_ms": "750",
        })
        p = ProfilePolicy.from_config(cfg)
        assert p.out_dir == "/tmp/prof" and p.mode == "slow"
        assert p.slow_query_ms == 750.0

    def test_from_env_spec(self, monkeypatch):
        monkeypatch.setenv("NDS_TPU_PROFILE", "query5@/tmp/d")
        p = ProfilePolicy.from_config(EngineConfig())
        assert p.queries == ("query5",) and p.out_dir == "/tmp/d"
        monkeypatch.setenv("NDS_TPU_PROFILE", "slow=250@/tmp/d")
        p = ProfilePolicy.from_config(EngineConfig())
        assert p.mode == "slow" and p.slow_query_ms == 250.0
        monkeypatch.setenv("NDS_TPU_PROFILE", "/tmp/bare")
        p = ProfilePolicy.from_config(EngineConfig())
        assert p.mode == "stall" and p.out_dir == "/tmp/bare"

    def test_profiler_history_arms_slow(self):
        prof = Profiler(ProfilePolicy("/t", "slow", slow_query_ms=100))
        assert prof.trigger_for("q") is None
        prof.observe("q", 150.0)
        assert prof.trigger_for("q") == "slow"


# --------------------------------------------------- flight recorder

class TestFlightRecorder:
    def test_ring_is_bounded(self, tmp_path):
        rec = fleet.FlightRecorder(str(tmp_path), maxlen=3)
        for i in range(7):
            rec.record(f"q{i}", "Completed")
        assert [e["query"] for e in rec.ring] == ["q4", "q5", "q6"]

    def test_dump_round_trips_schema(self, tmp_path):
        rec = fleet.FlightRecorder(str(tmp_path), rank=2, maxlen=4)
        tr = obs_trace.Tracer(enabled=True)
        with tr.span("query", query="q1") as sp:
            with tr.span("device.execute"):
                pass
        rec.record("q1", "Completed", sp, wall_ms=12.5,
                    metrics_delta={"counters": {"queries_total": 1}})
        rec.record("q2", "Failed")
        path = rec.dump("query-failed:q2")
        assert path and path.endswith("flight-r2.json")
        assert validate_flight_file(path) == []
        doc = json.load(open(path))
        assert doc["rank"] == 2 and doc["reason"] == "query-failed:q2"
        assert [e["query"] for e in doc["entries"]] == ["q1", "q2"]
        assert doc["entries"][0]["spans"]["name"] == "query"

    def test_repeat_dumps_keep_reason_history(self, tmp_path):
        rec = fleet.FlightRecorder(str(tmp_path), maxlen=4)
        rec.record("q", "Completed")
        rec.dump("first")
        path = rec.dump("second")
        doc = json.load(open(path))
        assert doc["reasons"] == ["first", "second"]
        assert doc["dumps"] == 2

    def test_env_zero_disables(self, tmp_path, monkeypatch):
        monkeypatch.setenv(fleet.FLIGHT_ENV, "0")
        rec = fleet.FlightRecorder(str(tmp_path))
        assert not rec.enabled
        rec.record("q", "Completed")
        assert rec.dump("x") is None
        assert not os.path.exists(rec.path)

    def test_arm_registers_stall_hook(self, tmp_path, monkeypatch):
        rec = fleet.arm_flight_recorder(str(tmp_path), rank=0)
        try:
            assert rec is not None
            rec.record("q7", "Completed")
            out = fleet._flight_stall_hook(str(tmp_path),
                                           {"query": "q7"})
            assert out and os.path.exists(out["flight"])
            assert validate_flight_file(out["flight"]) == []
        finally:
            fleet.disarm_flight_recorder()
        assert fleet._flight_stall_hook(str(tmp_path), {}) is None


# ------------------------------------------------ watchdog stall hooks

class TestStallHooks:
    def _stall_report(self, tmp_path):
        wd = watchdog.Watchdog(stall_s=0.01, run_dir=str(tmp_path))
        watchdog.reset()
        watchdog.beat("unit-x", query="qz", phase="exec")
        time.sleep(0.05)
        return wd.check_once()

    def test_hook_result_merges_into_report(self, tmp_path):
        def hook(run_dir, entry):
            return {"flight": os.path.join(run_dir, "fl.json"),
                    "profile": "/cap/1"}
        watchdog.register_stall_hook(hook)
        try:
            path = self._stall_report(tmp_path)
            doc = json.load(open(path))
            assert doc["flight"].endswith("fl.json")
            assert doc["profile"] == "/cap/1"
        finally:
            watchdog.unregister_stall_hook(hook)
            watchdog.reset()

    def test_hook_errors_never_kill_the_report(self, tmp_path):
        def bad(run_dir, entry):
            raise RuntimeError("boom")
        watchdog.register_stall_hook(bad)
        try:
            path = self._stall_report(tmp_path)
            doc = json.load(open(path))
            assert any("boom" in e for e in doc["hook_errors"])
            assert doc["query"] == "qz"  # report itself intact
        finally:
            watchdog.unregister_stall_hook(bad)
            watchdog.reset()


# ----------------------------------------------- schema + report blocks

class TestSchemaBlocks:
    BASE = {"query": "q", "queryStatus": ["Completed"],
            "queryTimes": [5], "startTime": 1, "env": {}}

    def test_profile_block_validates(self):
        good = {**self.BASE,
                "profile": {"path": "/p", "trigger": "slow",
                            "bytes": 10}}
        assert validate_summary(good) == []
        for bad in ({"path": "", "trigger": "query"},
                    {"path": "/p", "trigger": "nope"},
                    {"path": "/p"},
                    {"path": "/p", "trigger": "query", "bytes": -1}):
            assert validate_summary({**self.BASE, "profile": bad}), bad

    def test_flight_block_validates(self):
        good = {**self.BASE,
                "flight": {"path": "/f", "reason": "x", "entries": 3}}
        assert validate_summary(good) == []
        for bad in ({"path": ""}, {"reason": "x"},
                    {"path": "/f", "entries": -2}):
            assert validate_summary({**self.BASE, "flight": bad}), bad

    def test_flight_dump_negatives(self):
        assert validate_flight([]) != []
        assert validate_flight({"rank": -1}) != []
        good = {"rank": 0, "pid": 1, "reason": "r", "ts": 1.0,
                "entries": [{"query": "q", "status": "Completed",
                             "ts": 1.0}],
                "metrics": {}}
        assert validate_flight(good) == []
        assert validate_flight(
            {**good, "entries": [{"query": "", "status": "Completed",
                                  "ts": 1.0}]}) != []

    def test_report_attach_helpers(self):
        from nds_tpu.utils.report import BenchReport
        rep = BenchReport("q")
        rep.attach_profile({"path": "/p", "trigger": "query",
                            "bytes": 5})
        rep.attach_flight("/f", reason="r", entries=2)
        assert rep.summary["profile"] == {"path": "/p",
                                          "trigger": "query",
                                          "bytes": 5}
        assert rep.summary["flight"] == {"path": "/f", "reason": "r",
                                         "entries": 2}
        rep2 = BenchReport("q")
        rep2.attach_profile({})      # no capture -> no block
        rep2.attach_flight(None)
        assert "profile" not in rep2.summary
        assert "flight" not in rep2.summary


# -------------------------------------------------- export identities

class TestExportIds:
    def test_export_pid_override(self):
        tr = obs_trace.Tracer(enabled=True)
        with tr.span("query", query="x") as sp:
            pass
        try:
            obs_trace.set_export_pid(3)
            assert sp.to_events()[0]["pid"] == 3
        finally:
            obs_trace.set_export_pid(None)
        assert sp.to_events()[0]["pid"] == os.getpid()

    def test_tids_are_compact_and_stable(self):
        tr = obs_trace.Tracer(enabled=True)
        with tr.span("query") as sp:
            pass
        evs = sp.to_events()
        assert 1 <= evs[0]["tid"] <= len(obs_trace._TID_MAP)
        assert sp.to_events()[0]["tid"] == evs[0]["tid"]

    def test_stream_env_pins_export_pid(self, monkeypatch, tmp_path):
        monkeypatch.setenv("NDS_TPU_STREAM", "query_5")
        try:
            assert fleet.init_fleet(str(tmp_path)) is None
            assert obs_trace.export_pid() == 5
        finally:
            obs_trace.set_export_pid(None)
        # restarted incarnations keep the SAME lane
        monkeypatch.setenv("NDS_TPU_STREAM", "query_5#r1")
        try:
            fleet.init_fleet(str(tmp_path))
            assert obs_trace.export_pid() == 5
        finally:
            obs_trace.set_export_pid(None)


# -------------------------------------------- straggler attribution

def _query_event(pid, q, ts_us, dur_us):
    return {"name": "query", "cat": "query", "ph": "X", "ts": ts_us,
            "dur": dur_us, "pid": pid, "tid": 1, "args": {"query": q}}


def _dev_event(pid, ts_us, dur_us):
    return {"name": "device.execute", "cat": "device", "ph": "X",
            "ts": ts_us, "dur": dur_us, "pid": pid, "tid": 1,
            "args": {}}


class TestStragglers:
    def test_pairs_arrivals_and_blames_last_rank(self):
        events = [
            _query_event(0, "q1", 1_000_000, 500_000),
            _dev_event(0, 1_050_000, 400_000),
            _query_event(1, "q1", 1_010_000, 500_000),
            _dev_event(1, 1_250_000, 200_000),   # rank 1 arrives late
        ]
        s = analyze.straggler_stats(events)
        assert s["q1"]["slowest_rank"] == 1
        assert s["q1"]["wait_ms_by_rank"][0] == pytest.approx(200.0)
        assert s["q1"]["wait_ms_by_rank"][1] == pytest.approx(0.0)
        assert s["q1"]["skew_ms"] == pytest.approx(200.0)

    def test_single_rank_and_dup_instances_skipped(self):
        events = [_query_event(0, "q1", 0, 10),
                  _query_event(0, "q2", 0, 10),
                  _query_event(0, "q2", 50, 10),
                  _query_event(1, "q2", 0, 10)]
        s = analyze.straggler_stats(events)
        assert s == {}

    def _fleet_run_dir(self, tmp_path, aligned=True):
        """Synthetic 2-rank run dir: sidecars + shards + one rank-0
        summary whose spans give the query 300 ms of execute."""
        run = tmp_path / "run"
        run.mkdir()
        for rank, off in ((0, 0.0), (1, 2.0)):
            (run / f"fleet-r{rank}.json").write_text(json.dumps({
                "rank": rank, "world": 2, "host": f"h{rank}",
                "pid": 100 + rank, "boot_offset_s": off,
                "aligned": aligned,
                "trace_shard": f"trace-r{rank}.jsonl", "ts": 1.0}))
        # rank 1's shard is written 2 s AHEAD (its skewed clock); when
        # aligned, its events land back on rank 0's timeline
        shift = 2_000_000
        ev0 = [_query_event(0, "query9", 1_000_000, 400_000),
               _dev_event(0, 1_050_000, 300_000)]
        ev1 = [_query_event(1, "query9", 1_000_000 + shift, 400_000),
               _dev_event(1, 1_150_000 + shift, 300_000)]
        (run / "trace-r0.jsonl").write_text(
            "\n".join(json.dumps(e) for e in ev0) + "\n")
        (run / "trace-r1.jsonl").write_text(
            "\n".join(json.dumps(e) for e in ev1) + "\n")
        summary = {
            "query": "query9", "queryStatus": ["Completed"],
            "queryTimes": [400], "startTime": 1, "env": {},
            "spans": {"name": "query", "dur_ms": 400.0, "attrs": {},
                      "children": [
                          {"name": "device.execute", "dur_ms": 350.0,
                           "attrs": {}, "children": [
                               {"name": "device.run", "dur_ms": 300.0,
                                "attrs": {}, "children": []}]}]},
        }
        (run / "power-x-query9-1.json").write_text(json.dumps(summary))
        return str(run)

    def test_fleet_merge_moves_execute_into_straggler_wait(
            self, tmp_path):
        run = self._fleet_run_dir(tmp_path)
        a = analyze.analyze_run(run)
        assert a["fleet"]["world"] == 2
        row = a["queries"][0]
        # rank 1 arrived 100 ms after rank 0 (aligned clocks): that
        # 100 ms of rank 0's execute was really straggler wait
        assert row["categories"]["straggler_wait"] == pytest.approx(
            100.0, abs=1.0)
        assert row["categories"]["execute"] == pytest.approx(
            200.0, abs=1.0)
        assert row["straggler"]["slowest_rank"] == 1
        total = sum(row["categories"].values()) + row["residual_ms"]
        assert total == pytest.approx(row["wall_ms"], abs=1e-9)
        # alignment undid the 2 s skew: both ranks' spans overlap
        spans = {e["pid"]: e["ts"] for e in a["trace_events"]
                 if e["name"] == "query"}
        assert abs(spans[0] - spans[1]) < 500_000
        text = analyze.format_attribution(a)
        assert "stragl" in text and "straggler query9: rank 1" in text
        html = analyze.render_html(a)
        assert "Fleet timeline" in html and "rank 1" in html

    def test_unaligned_sidecars_merge_without_shift(self, tmp_path):
        run = self._fleet_run_dir(tmp_path, aligned=False)
        a = analyze.analyze_run(run)
        spans = {e["pid"]: e["ts"] for e in a["trace_events"]
                 if e["name"] == "query"}
        assert spans[1] - spans[0] == pytest.approx(2_000_000)


# ----------------------------------------------- fleet helper units

class TestFleetHelpers:
    def test_shard_path(self):
        assert fleet.shard_path("/r/trace.jsonl", 3) \
            == "/r/trace-r3.jsonl"
        assert fleet.shard_path("/r/trace", 0) == "/r/trace-r0.jsonl"

    def test_rank_info_single_process(self):
        info = fleet.rank_info()
        assert info["rank"] == 0 and info["world"] == 1
        assert info["pid"] == os.getpid()

    def test_clock_handshake_single_process(self):
        offsets = fleet.clock_handshake()
        assert offsets == [0.0]

    def test_load_fleet_ignores_junk(self, tmp_path):
        (tmp_path / "fleet-r0.json").write_text(
            json.dumps({"rank": 0, "world": 2}))
        (tmp_path / "fleet-rX.json").write_text("not json")
        (tmp_path / "other.json").write_text("{}")
        metas = fleet.load_fleet(str(tmp_path))
        assert [m["rank"] for m in metas] == [0]


# ----------------------------------------- exchange skew ratio gauge

class TestExchangeSkew:
    def test_skewed_shuffle_moves_the_gauge(self):
        """A heavily skewed key distribution through the distributed
        executor publishes exchange_skew_ratio > 1 after the query:
        every lineitem row carries ONE order key, so a single
        destination device receives the whole shuffle."""
        import numpy as np

        from nds_tpu.datagen import tpch
        from nds_tpu.engine.session import Session
        from nds_tpu.io.host_table import from_arrays
        from nds_tpu.nds_h.schema import get_schemas
        from nds_tpu.parallel.dist_exec import make_distributed_factory

        schemas = get_schemas()
        raw = tpch.gen_table("lineitem", 0.002)
        raw["l_orderkey"] = np.ones_like(raw["l_orderkey"])
        s = Session.for_nds_h(
            make_distributed_factory(shard_threshold=100))
        s.register_table(from_arrays("lineitem", schemas["lineitem"],
                                     raw))
        obs_metrics.gauge("exchange_skew_ratio").set(0)
        out = s.sql(
            "select l_orderkey, sum(l_quantity) as q from lineitem "
            "group by l_orderkey")
        assert len(out.to_pandas()) == 1
        val = obs_metrics.gauge("exchange_skew_ratio").value
        assert val > 1.5, val


# ------------------------------------- 2-process clock-aligned merge

SKEW_S = 30.0


def test_two_rank_clock_alignment(tmp_path):
    """Satellite acceptance: two REAL ranks with clocks skewed 30 s
    apart produce shards + sidecars whose merge puts the paired query
    spans back on one timeline — they overlap within tolerance after
    alignment, and are ~30 s apart without it."""
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    child = os.path.join(REPO, "tests", "_fleet_child.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "NDS_TPU_TRACE")}
    procs = [subprocess.Popen(
        [sys.executable, child, str(port), str(rank), "2", "2",
         str(tmp_path), str(SKEW_S), "session"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert f"FLEET_OK rank={rank}" in out, out[-4000:]

    run_dir = str(tmp_path / "run")
    metas = fleet.load_fleet(run_dir)
    assert [m["rank"] for m in metas] == [0, 1]
    assert all(m["aligned"] for m in metas)
    # the handshake measured the artificial skew (barrier jitter on
    # localhost is far under a second)
    assert metas[1]["boot_offset_s"] == pytest.approx(SKEW_S, abs=1.0)
    for rank in range(2):
        assert os.path.exists(
            os.path.join(run_dir, f"trace-r{rank}.jsonl"))

    def spans_by_query(events):
        out = {}
        for e in events:
            if e.get("name") == "query":
                q = (e.get("args") or {}).get("query")
                out.setdefault(q, {})[e["pid"]] = (
                    e["ts"], e["ts"] + e.get("dur", 0))
        return out

    aligned = spans_by_query(
        analyze.load_trace_events(run_dir, metas))
    raw = spans_by_query(analyze.load_trace_events(run_dir))
    assert set(aligned) == {"q1", "q6", "q3"}
    for q, by_rank in aligned.items():
        assert set(by_rank) == {0, 1}, f"{q} missing a rank lane"
        (s0, e0), (s1, e1) = by_rank[0], by_rank[1]
        # collectives pair the ranks inside each query: aligned spans
        # must overlap...
        assert max(s0, s1) < min(e0, e1), (q, by_rank)
        # ...while the unaligned shards sit ~SKEW_S apart
        rs0, rs1 = raw[q][0][0], raw[q][1][0]
        assert abs(rs1 - rs0) > (SKEW_S - 5) * 1e6
    strag = analyze.straggler_stats(
        analyze.load_trace_events(run_dir, metas))
    assert set(strag) == {"q1", "q6", "q3"}
    for q, s in strag.items():
        assert set(s["wait_ms_by_rank"]) == {0, 1}
        assert s["skew_ms"] < 30_000.0  # aligned: real skew, not clock
