"""Distributed engine tests on the virtual 8-device CPU mesh.

Tier: "multi-node without a cluster" (SURVEY.md §4) — every collective
(all_to_all exchange, all_gather replication, psum/pmin/pmax aggregation)
executes for real across 8 XLA host devices. Ground truth is the CPU
oracle, same epsilon contract as the single-device differential tests.
"""

import os

import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.slow

import jax

from nds_tpu.datagen import tpch
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h.schema import get_schemas
from nds_tpu.parallel.dist_exec import make_distributed_factory
from nds_tpu.parallel.exchange import exchange
from nds_tpu.parallel.mesh import DATA_AXIS, make_mesh

from tests.test_device_engine import assert_frames_close, run_query

SF = 0.01
# shard anything over 1k rows so lineitem/orders/partsupp/part/customer
# genuinely distribute at SF0.01
THRESHOLD = 1000


@pytest.fixture(scope="module")
def raw():
    return {t: tpch.gen_table(t, SF) for t in get_schemas()}


@pytest.fixture(scope="module")
def cpu_session(raw):
    schemas = get_schemas()
    sess = Session.for_nds_h()
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


@pytest.fixture(scope="module")
def dist_session(raw):
    schemas = get_schemas()
    sess = Session.for_nds_h(make_distributed_factory(
        n_devices=8, shard_threshold=THRESHOLD))
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_exchange_roundtrip():
    """Every valid row arrives exactly once, colocated by key hash."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from nds_tpu.parallel.dist_exec import shard_map

    mesh = make_mesh(8)
    n = 1024
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 500, n).astype(np.int64)
    vals = np.arange(n, dtype=np.int64)
    ok = rng.random(n) < 0.9

    def fn(k, v, m):
        (vo, ko), oko, over = exchange([v, k], k, m, 8, slack=2.0)
        return vo, ko, oko, over.reshape(1)

    f = shard_map(fn, mesh=mesh,
                  in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
                  out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                             P(DATA_AXIS)))
    vo, ko, oko, over = jax.jit(f)(jnp.asarray(keys), jnp.asarray(vals),
                                   jnp.asarray(ok))
    vo, ko, oko = np.asarray(vo), np.asarray(ko), np.asarray(oko)
    assert int(np.asarray(over).sum()) == 0
    got = sorted(vo[oko])
    assert got == sorted(vals[ok]), "rows lost or duplicated in exchange"
    # colocation: all rows of one key land on one device
    per_dev = len(ko) // 8
    dev_of = np.arange(len(ko)) // per_dev
    for k in np.unique(ko[oko]):
        devs = np.unique(dev_of[oko & (ko == k)])
        assert len(devs) == 1, f"key {k} split across devices {devs}"


# the FULL NDS-H set: every query must hold under distribution
# (VERDICT r1 weak #4 closed)
DIST_QUERIES = list(range(1, 23))


@pytest.mark.parametrize("qn", DIST_QUERIES)
def test_distributed_matches_oracle(qn, cpu_session, dist_session):
    exp = run_query(cpu_session, qn).to_pandas()
    got = run_query(dist_session, qn).to_pandas()
    assert_frames_close(got, exp, qn)


# NDS (TPC-DS) under distribution: ALL 99 templates (VERDICT r3 "next"
# #6) — every operator shape, including the year-over-year CTE monsters
# (q4/q11/q64/q74) whose wide plans and biggest intermediate capacities
# are exactly the ones most likely to break the exchange. Their virtual-
# mesh compiles are minutes each; the tier is slow-marked and the
# compiles amortize across runs via the persistent cache where the
# backend supports it.
def _all_nds_templates():
    from nds_tpu.nds import streams as nds_streams
    return nds_streams.available_templates()


NDS_DIST_QUERIES = _all_nds_templates()


@pytest.fixture(scope="module")
def nds_sessions():
    from nds_tpu.datagen import tpcds
    from nds_tpu.nds.schema import get_schemas as nds_schemas
    schemas = nds_schemas()
    cpu = Session.for_nds()
    dist = Session.for_nds(make_distributed_factory(
        n_devices=8, shard_threshold=THRESHOLD))
    for t in schemas:
        raw = tpcds.gen_table(t, SF)
        cpu.register_table(from_arrays(t, schemas[t], raw))
        dist.register_table(from_arrays(t, schemas[t], raw))
    return cpu, dist


@pytest.mark.parametrize("qn", NDS_DIST_QUERIES)
def test_nds_distributed_matches_oracle(qn, nds_sessions):
    from nds_tpu.nds import streams as nds_streams
    cpu, dist = nds_sessions
    sql = nds_streams.render_query(qn)
    for part, stmt in enumerate(
            [s for s in sql.split(";") if s.strip()], 1):
        exp = cpu.sql(stmt)
        got = dist.sql(stmt)
        if exp is None or got is None:
            continue
        assert_frames_close(got.to_pandas(), exp.to_pandas(),
                            f"nds{qn}_part{part}")


def test_left_join_nullable_key_distributed():
    """Left rows with NULL join keys must survive a both-sides-sharded
    exchange and null-extend (not silently drop to inner semantics)."""
    from nds_tpu.engine.types import INT32, Schema
    from nds_tpu.sql.planner import CatalogInfo

    n_fact, n_dim = 4096, 2048
    fact_schema = Schema.of(("f_id", INT32, False),
                            ("f_dim_sk", INT32, True),
                            ("f_val", INT32, False))
    dim_schema = Schema.of(("d_sk", INT32, False),
                           ("d_val", INT32, False))
    rng = np.random.default_rng(7)
    dim_sk = np.arange(1, n_dim + 1, dtype=np.int32)
    fk = rng.integers(1, n_dim + 1, n_fact).astype(np.int32)
    fk_valid = rng.random(n_fact) >= 0.1  # ~10% NULL FKs
    fact_arrays = {
        "f_id": np.arange(n_fact, dtype=np.int32),
        "f_dim_sk": np.where(fk_valid, fk, 0).astype(np.int32),
        "f_dim_sk#null": fk_valid,
        "f_val": rng.integers(0, 100, n_fact).astype(np.int32),
    }
    dim_arrays = {"d_sk": dim_sk,
                  "d_val": (dim_sk * 3).astype(np.int32)}
    cat = CatalogInfo({"fact": fact_schema, "dim": dim_schema},
                      {"dim": ["d_sk"], "fact": ["f_id"]},
                      {"fact": n_fact, "dim": n_dim})
    sql = ("select f_id, f_val, d_val from fact "
           "left join dim on f_dim_sk = d_sk order by f_id")

    def build(factory=None):
        s = Session(cat, factory)
        s.register_table(from_arrays("fact", fact_schema, fact_arrays))
        s.register_table(from_arrays("dim", dim_schema, dim_arrays))
        return s

    exp = build().sql(sql).to_pandas()
    assert len(exp) == n_fact, "oracle must keep every left row"
    got = build(make_distributed_factory(
        n_devices=8, shard_threshold=1000)).sql(sql).to_pandas()
    assert_frames_close(got, exp, "null-key left join")
    # the NULL-FK rows are exactly the null-extended ones
    assert int(got["d_val"].isna().sum()) == int((~fk_valid).sum())


def test_hierarchical_exchange_dcn_ici():
    """Two-stage DCN/ICI shuffle on a 2x4 virtual (host, lane) mesh:
    no rows lost, overflow counted, and every key colocated on exactly
    one (host, lane) device — the same contract as the flat exchange."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from nds_tpu.parallel.dist_exec import shard_map
    from nds_tpu.parallel.exchange import exchange_hierarchical
    from nds_tpu.parallel.mesh import HOST_AXIS, make_multihost_mesh

    H, D = 2, 4
    mesh = make_multihost_mesh(H, D)
    n = 2048
    per = n // (H * D)
    rng = np.random.default_rng(11)
    keys = rng.integers(1, 500, n).astype(np.int64)
    vals = np.arange(n, dtype=np.int64)
    ok = rng.random(n) >= 0.05

    both_axes = P((HOST_AXIS, DATA_AXIS))

    def fn(k, v, o):
        k, v, o = k.reshape(-1), v.reshape(-1), o.reshape(-1)
        outs, out_ok, over = exchange_hierarchical(
            [v, k], k, o, H, D, slack=3.0)
        m = outs[0].shape[0]
        return (outs[0].reshape(1, m), outs[1].reshape(1, m),
                out_ok.reshape(1, m), jnp.reshape(over, (1, 1)))

    f = shard_map(fn, mesh=mesh,
                  in_specs=(both_axes,) * 3,
                  out_specs=(both_axes,) * 4)
    k2 = jnp.asarray(keys).reshape(H * D, per)
    v2 = jnp.asarray(vals).reshape(H * D, per)
    o2 = jnp.asarray(ok).reshape(H * D, per)
    vo, ko, oko, over = jax.jit(f)(k2, v2, o2)
    vo, ko, oko = (np.asarray(x) for x in (vo, ko, oko))
    assert int(np.asarray(over).sum()) == 0
    got = sorted(vo[oko].tolist())
    assert got == sorted(vals[ok].tolist()), "rows lost or duplicated"
    # colocation: every key lives on exactly one (host, lane) device
    for k in np.unique(ko[oko]):
        devs = {i for i in range(H * D) if (ko[i][oko[i]] == k).any()}
        assert len(devs) == 1, f"key {k} split across devices {devs}"


def _launch_multihost(nproc: int, ndev: int) -> None:
    """Launch nproc OS processes x ndev virtual CPU devices into one
    jax.distributed world and assert every rank completes its
    distributed-vs-oracle sweep (tests/_multihost_child.py)."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    child = os.path.join(os.path.dirname(__file__),
                         "_multihost_child.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, child, str(port), str(rank), str(nproc),
         str(ndev)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for rank in range(nproc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_OK rank={rank}" in out, out[-4000:]


def test_two_process_multihost():
    """REAL multi-process DCN axis: two OS processes x 4 virtual CPU
    devices join one jax.distributed world (8 global devices) and run
    distributed queries against per-process oracles. This is the launch
    path `--backend distributed` takes under a multi-host launcher
    (parallel/multihost.py; the reference analog is the executor
    topology config, `nds/base.template:29-31`)."""
    _launch_multihost(2, 4)


def test_four_process_multihost():
    """4-process world (4 x 2 devices): more DCN participants than the
    2-process tier — collective membership, rank-0 gating, and the
    global-array shard loading must hold beyond the pairwise case."""
    _launch_multihost(4, 2)


MULTIHOST_QUERIES = [1, 3, 5, 13, 16, 18]


@pytest.fixture(scope="module")
def multihost_session(raw):
    """Executor over a 2x4 (host, lane) mesh: collectives span both
    axes, the exchange runs its hierarchical DCN-then-ICI form."""
    from nds_tpu.parallel.mesh import make_multihost_mesh
    schemas = get_schemas()
    sess = Session.for_nds_h(make_distributed_factory(
        mesh=make_multihost_mesh(2, 4), shard_threshold=THRESHOLD))
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


@pytest.mark.parametrize("qn", MULTIHOST_QUERIES)
def test_multihost_mesh_matches_oracle(qn, cpu_session,
                                       multihost_session):
    exp = run_query(cpu_session, qn).to_pandas()
    got = run_query(multihost_session, qn).to_pandas()
    assert_frames_close(got, exp, f"2d-{qn}")


def test_replicated_scan_reduction_on_mesh(raw, cpu_session):
    """Survivor reduction on the mesh: filtered REPLICATED scans shrink
    to reduced pow2 capacity (sharded tables keep the shard layout);
    results must match the oracle and the shrink must engage."""
    from nds_tpu.engine.device_exec import _ReducedScan
    from nds_tpu.parallel.dist_exec import DistributedExecutor

    class SmallReduce(DistributedExecutor):
        REDUCE_MIN_ROWS = 1

    holder: dict = {}

    def factory(tables):
        ex = holder.get("ex")
        if ex is None or ex.tables is not tables:
            # facts shard; dimensions replicate — so the filtered
            # customer/part scans are the replicated-reduction targets
            ex = SmallReduce(tables, n_devices=8,
                             shard_tables={"lineitem", "orders",
                                           "partsupp"})
            holder["ex"] = ex
        return ex

    schemas = get_schemas()
    sess = Session.for_nds_h(factory)
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    # q3: filtered replicated customer against two sharded facts;
    # q10: date-filtered SHARDED orders whose broadcast-sized survivor
    # set must flip to a replicated reduced build (the AQE-style
    # broadcast-join move)
    for qn in (3, 10):
        exp = run_query(cpu_session, qn).to_pandas()
        got = run_query(sess, qn).to_pandas()
        assert_frames_close(got, exp, f"reduce-dist-{qn}")
    ex = holder["ex"]
    reduced = [v for v in ex._scan_views.values()
               if isinstance(v, _ReducedScan)]
    assert reduced, "no scan reduced on the mesh"
    for rv in reduced:
        assert rv.capacity & (rv.capacity - 1) == 0
    # engagement is proven by UPLOADED reduced buffers (cache entries
    # exist even when the gate rejects or the trace never reads them)
    up = {k.split(".", 1)[0].split("@", 1)[0]
          for k in ex._buffers if "@" in k.split(".", 1)[0]}
    assert any(not ex._is_sharded(t) for t in up), \
        "replicated-dimension reduction never uploaded a buffer"
    assert any(ex._is_sharded(t) for t in up), \
        "sharded->broadcast reduction never uploaded a buffer"


def test_compiled_program_lru_eviction(raw, cpu_session):
    """A 99-query power run must not accumulate compiled shard_map
    programs unboundedly (the full-tier process OOMed at 130GB):
    entries evict LRU past MAX_COMPILED, and an evicted query
    recompiles correctly on its next run."""
    from nds_tpu.parallel.dist_exec import DistributedExecutor

    class TwoSlots(DistributedExecutor):
        MAX_COMPILED = 2

    holder: dict = {}

    def factory(tables):
        ex = holder.get("ex")
        if ex is None or ex.tables is not tables:
            ex = TwoSlots(tables, n_devices=8,
                          shard_threshold=THRESHOLD)
            holder["ex"] = ex
        return ex

    schemas = get_schemas()
    sess = Session.for_nds_h(factory)
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    oracle = {}
    for qn in (6, 1, 3):
        oracle[qn] = run_query(cpu_session, qn).to_pandas()
        got = run_query(sess, qn).to_pandas()
        assert_frames_close(got, oracle[qn], f"lru-{qn}")
    ex = holder["ex"]
    assert len(ex._compiled) <= 2
    # q6 was evicted; re-running it must recompile and still match
    got = run_query(sess, 6).to_pandas()
    assert_frames_close(got, oracle[6], "lru-q6-again")
