"""Distributed engine tests on the virtual 8-device CPU mesh.

Tier: "multi-node without a cluster" (SURVEY.md §4) — every collective
(all_to_all exchange, all_gather replication, psum/pmin/pmax aggregation)
executes for real across 8 XLA host devices. Ground truth is the CPU
oracle, same epsilon contract as the single-device differential tests.
"""

import numpy as np
import pandas as pd
import pytest

import jax

from nds_tpu.datagen import tpch
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h.schema import get_schemas
from nds_tpu.parallel.dist_exec import make_distributed_factory
from nds_tpu.parallel.exchange import exchange
from nds_tpu.parallel.mesh import DATA_AXIS, make_mesh

from tests.test_device_engine import assert_frames_close, run_query

SF = 0.01
# shard anything over 1k rows so lineitem/orders/partsupp/part/customer
# genuinely distribute at SF0.01
THRESHOLD = 1000


@pytest.fixture(scope="module")
def raw():
    return {t: tpch.gen_table(t, SF) for t in get_schemas()}


@pytest.fixture(scope="module")
def cpu_session(raw):
    schemas = get_schemas()
    sess = Session.for_nds_h()
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


@pytest.fixture(scope="module")
def dist_session(raw):
    schemas = get_schemas()
    sess = Session.for_nds_h(make_distributed_factory(
        n_devices=8, shard_threshold=THRESHOLD))
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_exchange_roundtrip():
    """Every valid row arrives exactly once, colocated by key hash."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from nds_tpu.parallel.dist_exec import shard_map

    mesh = make_mesh(8)
    n = 1024
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 500, n).astype(np.int64)
    vals = np.arange(n, dtype=np.int64)
    ok = rng.random(n) < 0.9

    def fn(k, v, m):
        (vo, ko), oko, over = exchange([v, k], k, m, 8, slack=2.0)
        return vo, ko, oko, over.reshape(1)

    f = shard_map(fn, mesh=mesh,
                  in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
                  out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                             P(DATA_AXIS)))
    vo, ko, oko, over = jax.jit(f)(jnp.asarray(keys), jnp.asarray(vals),
                                   jnp.asarray(ok))
    vo, ko, oko = np.asarray(vo), np.asarray(ko), np.asarray(oko)
    assert int(np.asarray(over).sum()) == 0
    got = sorted(vo[oko])
    assert got == sorted(vals[ok]), "rows lost or duplicated in exchange"
    # colocation: all rows of one key land on one device
    per_dev = len(ko) // 8
    dev_of = np.arange(len(ko)) // per_dev
    for k in np.unique(ko[oko]):
        devs = np.unique(dev_of[oko & (ko == k)])
        assert len(devs) == 1, f"key {k} split across devices {devs}"


# representative coverage: scan/filter/agg (1,6), joins incl. cyclic
# graph (5), expanding left join (13), semi/anti residual (21), scalar
# subqueries + exchange agg (15, 17), distinct count (16), union view
# (15 handled), correlated (2, 20), heavy multi-join (9)
DIST_QUERIES = [1, 2, 3, 5, 6, 9, 13, 15, 16, 17, 18, 20, 21, 22]


@pytest.mark.parametrize("qn", DIST_QUERIES)
def test_distributed_matches_oracle(qn, cpu_session, dist_session):
    exp = run_query(cpu_session, qn).to_pandas()
    got = run_query(dist_session, qn).to_pandas()
    assert_frames_close(got, exp, qn)
