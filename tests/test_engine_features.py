"""Unit tests for the TPC-DS-motivated engine features: window
functions, ROLLUP/GROUPING SETS, INTERSECT/EXCEPT, stddev, coalesce.

Each feature is checked three ways where practical: CPU oracle vs
hand-computed pandas, then device engine vs CPU oracle (the standard
differential contract).
"""

import numpy as np
import pandas as pd
import pytest

from nds_tpu.engine.device_exec import make_device_factory
from nds_tpu.engine.session import Session
from nds_tpu.engine.types import INT32, INT64, Schema, decimal, varchar
from nds_tpu.io.host_table import from_arrays
from nds_tpu.sql.planner import CatalogInfo

from tests.test_device_engine import assert_frames_close

N = 500


def _catalog():
    sales = Schema.of(
        ("s_id", INT32, False), ("s_cat", varchar(10), False),
        ("s_store", INT32, False), ("s_qty", INT32, True),
        ("s_price", decimal(12, 2), False), ("s_day", INT32, False))
    other = Schema.of(("o_cat", varchar(10), False),
                      ("o_store", INT32, False))
    return CatalogInfo({"sales": sales, "other": other},
                       {"sales": ["s_id"]},
                       {"sales": N, "other": 60})


def _data():
    rng = np.random.default_rng(42)
    cats = np.array(["alpha", "beta", "gamma", "delta"], dtype=object)
    qty = rng.integers(1, 50, N)
    qty_valid = rng.random(N) >= 0.08
    sales = {
        "s_id": np.arange(N, dtype=np.int32),
        "s_cat": cats[rng.integers(0, 4, N)],
        "s_store": rng.integers(1, 6, N).astype(np.int32),
        "s_qty": np.where(qty_valid, qty, 0).astype(np.int32),
        "s_qty#null": qty_valid,
        "s_price": rng.integers(100, 99999, N).astype(np.int64),
        "s_day": rng.integers(1, 31, N).astype(np.int32),
    }
    other = {
        "o_cat": cats[rng.integers(0, 3, 60)],
        "o_store": rng.integers(1, 8, 60).astype(np.int32),
    }
    return sales, other


@pytest.fixture(scope="module")
def sessions():
    cat = _catalog()
    sales, other = _data()

    def build(factory=None):
        s = Session(cat, factory)
        s.register_table(from_arrays(
            "sales", cat.schemas["sales"], sales))
        s.register_table(from_arrays(
            "other", cat.schemas["other"], other))
        return s

    return build(), build(make_device_factory())


@pytest.fixture(scope="module")
def pdf():
    sales, _ = _data()
    df = pd.DataFrame({k: v for k, v in sales.items()
                       if not k.endswith("#null")})
    df["s_qty"] = df["s_qty"].where(sales["s_qty#null"])
    return df


def both(sessions, sql):
    cpu, dev = sessions
    exp = cpu.sql(sql).to_pandas()
    got = dev.sql(sql).to_pandas()
    assert_frames_close(got, exp, sql[:40])
    return exp


# ---------------------------------------------------------------- windows

def test_rank_window(sessions, pdf):
    sql = ("select s_id, rank() over (partition by s_cat "
           "order by s_price desc) rk from sales order by s_id")
    exp = both(sessions, sql)
    pr = pdf.sort_values("s_id")
    expected = pdf.groupby("s_cat")["s_price"].rank(
        method="min", ascending=False).astype(np.int64)
    assert list(exp.sort_values("s_id")["rk"]) == list(
        expected[pr.index])


def test_dense_rank_and_row_number(sessions, pdf):
    sql = ("select s_id, dense_rank() over (partition by s_store "
           "order by s_day) dr, row_number() over (partition by "
           "s_store order by s_day, s_id) rn from sales order by s_id")
    exp = both(sessions, sql)
    dr = pdf.groupby("s_store")["s_day"].rank(
        method="dense").astype(np.int64)
    assert list(exp.sort_values("s_id")["dr"]) == list(
        dr[pdf.sort_values("s_id").index])


def test_partition_sum_avg(sessions, pdf):
    sql = ("select s_id, sum(s_price) over (partition by s_cat) tot, "
           "avg(s_qty) over (partition by s_store) aq "
           "from sales order by s_id")
    exp = both(sessions, sql)
    tot = pdf.groupby("s_cat")["s_price"].transform("sum") / 100.0
    np.testing.assert_allclose(
        exp.sort_values("s_id")["tot"].to_numpy(dtype=float),
        tot[pdf.sort_values("s_id").index].to_numpy(), rtol=1e-9)
    aq = pdf.groupby("s_store")["s_qty"].transform("mean")
    np.testing.assert_allclose(
        exp.sort_values("s_id")["aq"].to_numpy(dtype=float),
        aq[pdf.sort_values("s_id").index].to_numpy(), rtol=1e-9)


def test_cumulative_window(sessions, pdf):
    sql = ("select s_id, sum(s_price) over (partition by s_cat "
           "order by s_id rows between unbounded preceding and "
           "current row) c from sales order by s_id")
    exp = both(sessions, sql)
    c = pdf.sort_values("s_id").groupby("s_cat")["s_price"].cumsum() / 100
    np.testing.assert_allclose(
        exp.sort_values("s_id")["c"].to_numpy(dtype=float),
        c.to_numpy(), rtol=1e-9)


def test_range_default_frame_ties_share_value(sessions, pdf):
    # default frame with ORDER BY: peers (same s_day) share the
    # peer-group-final running sum
    sql = ("select s_id, sum(s_qty) over (partition by s_cat "
           "order by s_day) rs from sales order by s_id")
    exp = both(sessions, sql)
    df = pdf.copy()
    base = (df.sort_values(["s_cat", "s_day"], kind="stable")
            .groupby("s_cat")["s_qty"].cumsum())
    df["_cum"] = base
    peers = df.groupby(["s_cat", "s_day"])["_cum"].transform("max")
    np.testing.assert_allclose(
        exp.sort_values("s_id")["rs"].to_numpy(dtype=float),
        peers[pdf.sort_values("s_id").index].to_numpy(), rtol=1e-9)


def test_window_over_aggregate(sessions, pdf):
    sql = ("select s_cat, s_store, sum(s_price) sp, "
           "rank() over (partition by s_cat order by sum(s_price) desc) "
           "rk from sales group by s_cat, s_store order by s_cat, rk")
    exp = both(sessions, sql)
    g = pdf.groupby(["s_cat", "s_store"])["s_price"].sum().reset_index()
    g["rk"] = g.groupby("s_cat")["s_price"].rank(
        method="min", ascending=False).astype(np.int64)
    g = g.sort_values(["s_cat", "rk"])
    assert list(exp["rk"]) == list(g["rk"])


# ------------------------------------------------------------------ rollup

def test_rollup_counts(sessions, pdf):
    sql = ("select s_cat, s_store, count(*) c, sum(s_price) sp "
           "from sales group by rollup(s_cat, s_store) "
           "order by s_cat nulls last, s_store nulls last")
    exp = both(sessions, sql)
    # grand-total row: NULL cat, NULL store, count == N
    total = exp[exp["s_cat"].isna() & exp["s_store"].isna()]
    assert len(total) == 1
    assert int(total["c"].iloc[0]) == N
    # per-cat subtotal rows (store IS NULL, cat NOT NULL)
    sub = exp[exp["s_cat"].notna() & exp["s_store"].isna()]
    gc = pdf.groupby("s_cat").size()
    assert dict(zip(sub["s_cat"], sub["c"].astype(int))) == dict(gc)
    # full detail rows count
    detail = exp[exp["s_cat"].notna() & exp["s_store"].notna()]
    assert len(detail) == len(pdf.groupby(["s_cat", "s_store"]))


def test_grouping_function(sessions, pdf):
    sql = ("select s_cat, grouping(s_cat) g1, grouping(s_store) g2, "
           "count(*) c from sales group by rollup(s_cat, s_store) "
           "order by g1, g2, s_cat nulls last")
    exp = both(sessions, sql)
    assert set(zip(exp["g1"], exp["g2"])) == {(0, 0), (0, 1), (1, 1)}


def test_grouping_sets(sessions, pdf):
    sql = ("select s_cat, s_store, count(*) c from sales "
           "group by grouping sets((s_cat), (s_store)) "
           "order by s_cat nulls last, s_store nulls last")
    exp = both(sessions, sql)
    assert len(exp) == pdf["s_cat"].nunique() + pdf["s_store"].nunique()


def test_rollup_with_rank_window(sessions, pdf):
    # the q36/q70/q86 shape: rank within rollup level
    sql = ("select s_cat, s_store, sum(s_price) sp, "
           "grouping(s_cat) + grouping(s_store) lochierarchy, "
           "rank() over (partition by grouping(s_cat) + "
           "grouping(s_store) order by sum(s_price) desc) rk "
           "from sales group by rollup(s_cat, s_store) "
           "order by lochierarchy desc, rk")
    both(sessions, sql)


# ----------------------------------------------------------------- set ops

def test_intersect(sessions, pdf):
    sql = ("select s_cat, s_store from sales intersect "
           "select o_cat, o_store from other order by s_cat, s_store")
    exp = both(sessions, sql)
    _, other = _data()
    l = set(zip(pdf["s_cat"], pdf["s_store"]))
    r = set(zip(other["o_cat"], other["o_store"]))
    assert len(exp) == len(l & r)


def test_except(sessions, pdf):
    sql = ("select s_cat, s_store from sales except "
           "select o_cat, o_store from other order by s_cat, s_store")
    exp = both(sessions, sql)
    _, other = _data()
    l = set(zip(pdf["s_cat"], pdf["s_store"]))
    r = set(zip(other["o_cat"], other["o_store"]))
    assert len(exp) == len(l - r)


# ------------------------------------------------------------- aggregates

def test_stddev_samp(sessions, pdf):
    sql = ("select s_cat, stddev_samp(s_qty) sd from sales "
           "group by s_cat order by s_cat")
    exp = both(sessions, sql)
    sd = pdf.groupby("s_cat")["s_qty"].std(ddof=1)
    np.testing.assert_allclose(exp["sd"].to_numpy(dtype=float),
                               sd.to_numpy(), rtol=1e-9)


def test_coalesce(sessions, pdf):
    sql = ("select s_id, coalesce(s_qty, 0) q from sales order by s_id")
    exp = both(sessions, sql)
    q = pdf["s_qty"].fillna(0).astype(np.int64)
    assert list(exp.sort_values("s_id")["q"].astype(int)) == list(q)


# -------------------------------------------------------------- M:N joins

def test_many_to_many_inner_join(sessions, pdf):
    """Neither side unique on the join key: the device engine must
    expand match ranges (slack-capacity path), not pick one match."""
    sql = ("select a.s_id id_a, b.s_id id_b from sales a, sales b "
           "where a.s_store = b.s_store "
           "and a.s_cat = 'alpha' and b.s_cat = 'beta' "
           "and a.s_day = 1 and b.s_day <= 3 "
           "order by id_a, id_b")
    exp = both(sessions, sql)
    a = pdf[(pdf.s_cat == "alpha") & (pdf.s_day == 1)]
    b = pdf[(pdf.s_cat == "beta") & (pdf.s_day <= 3)]
    m = a.merge(b, on=["s_store"])
    # exact pandas-merge cardinality is the M:N correctness contract
    # (the old unique-build path would keep one match per probe row)
    assert len(exp) == len(m)
    assert exp["id_a"].duplicated().any(), "join must expand matches"


# ---------------------------------------------- full outer join / strings

def test_full_outer_join_where_applies_post_join(sessions, pdf):
    """WHERE over a FULL OUTER JOIN filters null-extended rows too —
    no pushdown below the preserving join (r2 review repro)."""
    sql = ("with a as (select s_store k1, sum(s_qty) v1 from sales "
           "where s_cat = 'alpha' group by s_store), "
           "b as (select s_store k2, sum(s_qty) v2 from sales "
           "where s_cat = 'beta' group by s_store) "
           "select k1, v1, k2, v2 from a full outer join b "
           "on (a.k1 = b.k2) where v1 > 0 order by k1")
    exp = both(sessions, sql)
    # every surviving row has a non-null v1 (null-extended b-only rows
    # must be filtered out)
    assert exp["v1"].notna().all()


def test_full_outer_join_preserves_both_sides(sessions):
    sql = ("with a as (select s_store k1 from sales where s_store <= 3 "
           "group by s_store), "
           "b as (select s_store k2 from sales where s_store >= 3 "
           "group by s_store) "
           "select k1, k2 from a full outer join b on (a.k1 = b.k2) "
           "order by k1, k2")
    exp = both(sessions, sql)
    assert len(exp) == 5  # stores 1..5: 1,2 a-only; 3 both; 4,5 b-only
    assert exp["k1"].isna().sum() == 2
    assert exp["k2"].isna().sum() == 2


def test_upper_merges_collided_dictionary_codes(sessions):
    """upper() must dedupe dictionary entries that become equal, or
    GROUP BY over codes splits equal strings (r2 review repro)."""
    sql = ("select upper(s_cat) u, count(*) c from sales "
           "group by upper(s_cat) order by u")
    exp = both(sessions, sql)
    assert list(exp["u"]) == sorted(exp["u"])
    assert len(exp) == 4  # ALPHA/BETA/DELTA/GAMMA, no split groups


def test_concat_literal_prefix(sessions):
    sql = ("select 'cat_' || s_cat || '!' tag, count(*) c from sales "
           "group by 'cat_' || s_cat || '!' order by tag")
    exp = both(sessions, sql)
    assert all(t.startswith("cat_") and t.endswith("!")
               for t in exp["tag"])


# ------------------------------------------------- config-knob consumers

def test_execute_async_pipelines_queries(sessions):
    """engine.concurrent_tasks' mechanism: N dispatched queries in
    flight at once, results collected later, identical to sync."""
    cpu, dev = sessions
    sqls = [
        "select s_cat, sum(s_price) t from sales group by s_cat order by s_cat",
        "select count(*) c from sales where s_qty > 10",
        "select s_store, avg(s_qty) a from sales group by s_store order by s_store",
    ]
    handles = [dev.sql_async(q) for q in sqls]
    for q, h in zip(sqls, handles):
        assert_frames_close(h.result().to_pandas(),
                            cpu.sql(q).to_pandas(), q[:30])


def test_sql_async_on_cpu_backend_is_completed_handle(sessions):
    cpu, _dev = sessions
    h = cpu.sql_async("select count(*) c from sales")
    assert int(h.result().to_pandas()["c"][0]) == N


def test_precision_f32_compute(sessions):
    """engine.precision=f32 consumer: float compute runs in float32 (the
    floats-mode fast path); results stay within float32 tolerance of the
    f64 oracle."""
    from nds_tpu.engine.device_exec import make_device_factory
    cpu, dev = sessions
    f32 = Session(dev.catalog, make_device_factory("f32"))
    for t in dev.tables.values():
        f32.register_table(t)
    sql = "select s_cat, avg(s_qty) a from sales group by s_cat order by s_cat"
    got = f32.sql(sql).to_pandas()
    exp = cpu.sql(sql).to_pandas()
    assert got["a"].to_numpy().dtype == np.float32
    np.testing.assert_allclose(got["a"].to_numpy(dtype=float),
                               exp["a"].to_numpy(dtype=float), rtol=1e-5)


def test_precision_rejects_unknown():
    with pytest.raises(ValueError):
        make_device_factory("f16")


def test_make_session_precision_only_in_floats_mode(tmp_path):
    """Decimal mode must pin f64 regardless of engine.precision — the
    pipeline threads the precision into every device-side placement."""
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    from nds_tpu.nds.power import SUITE
    cfg = EngineConfig(overrides={"engine.backend": "tpu",
                                  "engine.precision": "f32"})
    sess = power_core.make_session(SUITE, cfg)
    pipe = sess._executor_factory({})
    assert pipe._executor("device").float_dtype is None  # f64
    cfg2 = EngineConfig(overrides={"engine.backend": "tpu",
                                   "engine.floats": "true",
                                   "engine.precision": "f32"})
    sess2 = power_core.make_session(SUITE, cfg2)
    pipe2 = sess2._executor_factory({})
    import jax.numpy as jnp
    assert pipe2._executor("device").float_dtype == jnp.float32
    # both device-side rungs share the precision
    assert pipe2._executor("chunked").float_dtype == jnp.float32


class TestChunkedExecution:
    """Out-of-core path (SURVEY.md §7 hard part 4): tables above the
    stream threshold never upload whole; chunked scan+filter reduces
    them host-side, phase B runs on survivors only."""

    @pytest.fixture(scope="class")
    def chunked(self, sessions):
        from nds_tpu.engine.chunked_exec import make_chunked_factory
        cpu, dev = sessions
        # threshold 1 byte: EVERY table streams; chunk of 64 rows
        # forces a multi-chunk loop (N=500 -> 8 chunks)
        sess = Session(dev.catalog,
                       make_chunked_factory(stream_bytes=1,
                                            chunk_rows=64))
        for t in dev.tables.values():
            sess.register_table(t)
        return cpu, sess

    @pytest.mark.parametrize("sql", [
        "select s_cat, sum(s_price) t from sales where s_qty > 10 "
        "group by s_cat order by s_cat",
        "select count(*) c from sales where s_day between 5 and 12",
        "select s_store, count(*) c from sales, other "
        "where s_store = o_store and s_qty is not null "
        "group by s_store order by s_store",
        # no filter at all: reduction keeps everything, still correct
        "select s_cat, min(s_day) m from sales group by s_cat "
        "order by s_cat",
        # IS NULL predicate: NULL-mask semantics through the chunk scan
        "select count(*) c from sales where s_qty is null",
    ])
    def test_matches_oracle(self, chunked, sql):
        cpu, sess = chunked
        assert_frames_close(sess.sql(sql).to_pandas(),
                            cpu.sql(sql).to_pandas(), sql[:40])

    def test_scalar_subquery_filter(self, chunked):
        """q32/q92 shape: a pushed-down predicate referencing a scalar
        subquery is not chunk-evaluable — it must be skipped in phase A
        (other predicates still reduce) and re-applied in phase B."""
        cpu, sess = chunked
        sql = ("select count(*) c from sales where s_day < 10 and "
               "s_price > (select avg(s_price) from sales)")
        assert_frames_close(sess.sql(sql).to_pandas(),
                            cpu.sql(sql).to_pandas(), "scalar-filter")

    def test_streamed_table_never_uploads_whole(self, chunked):
        """The memory contract: the chunked executor's own buffer pool
        must hold no full column of a streamed table."""
        _cpu, sess = chunked
        sql = ("select s_cat, sum(s_price) t from sales where s_qty > 40 "
               "group by s_cat order by s_cat")
        sess.sql(sql)
        ex = sess._executor_factory(sess.tables)
        subs = list(ex._reduced.values())
        assert subs
        sub = subs[-1]
        from nds_tpu.engine.chunked_exec import _PartialAggExecutor
        import numpy as np
        full = ex.tables["sales"]
        # THIS plan's executor must hold no full-length sales buffer
        # (identity reductions from OTHER queries — e.g. a global avg
        # subquery needing every row — may legitimately share the pool)
        for k, v in sub._buffers.items():
            if k.startswith("sales."):
                assert v.shape[0] < full.nrows, k
        if isinstance(sub, _PartialAggExecutor):
            # partial-agg phase B: the big table is never uploaded at
            # all — only the per-chunk partials are
            assert "__pa_partials__" in sub.tables
            assert not any(k.startswith("sales.") for k in sub._buffers)
            assert sub.tables["__pa_partials__"].nrows < full.nrows
        else:
            # survivor-reduction phase B holds only the reduced rows
            expect = int(((np.asarray(full.column("s_qty").values) > 40)
                          & full.column("s_qty").null_mask).sum())
            assert sub.tables["sales"].nrows == expect

    @pytest.fixture(scope="class")
    def chunked_pa(self, sessions):
        """stream_bytes sized so ONLY `sales` streams (other fits):
        exercises the partial-aggregation split with joins below the
        aggregate."""
        from nds_tpu.engine.chunked_exec import make_chunked_factory
        cpu, dev = sessions
        sess = Session(dev.catalog,
                       make_chunked_factory(stream_bytes=2000,
                                            chunk_rows=64))
        for t in dev.tables.values():
            sess.register_table(t)
        return cpu, sess

    @pytest.mark.parametrize("sql", [
        # avg must recompose exactly from per-chunk (sum, count)
        "select s_cat, avg(s_qty) a, count(*) c from sales "
        "group by s_cat order by s_cat",
        # global aggregate (no group keys), all mergeable funcs
        "select sum(s_price) t, count(*) c, avg(s_qty) a, "
        "min(s_day) mn, max(s_day) mx from sales",
        # join below the aggregate: build side replicated, probe chunked
        "select s_cat, sum(s_qty) q from sales, other "
        "where s_store = o_store group by s_cat order by s_cat",
        # count(col) skips NULLs per chunk and merges by sum
        "select s_store, count(s_qty) c from sales group by s_store "
        "order by s_store",
    ])
    def test_partial_agg_matches_oracle(self, chunked_pa, sql):
        cpu, sess = chunked_pa
        from nds_tpu.engine.chunked_exec import _PartialAggExecutor
        assert_frames_close(sess.sql(sql).to_pandas(),
                            cpu.sql(sql).to_pandas(), sql[:40])
        ex = sess._executor_factory(sess.tables)
        assert any(isinstance(s, _PartialAggExecutor)
                   for s in ex._reduced.values()), \
            "partial-agg path was expected to engage"

    def test_partial_agg_semijoin_right_falls_back(self, sessions):
        """q22 regression: when the STREAMED table is the right side of
        a NOT EXISTS, partial aggregation must not engage (membership
        against one chunk at a time inflates the anti join)."""
        from nds_tpu.engine.chunked_exec import (
            _PartialAggExecutor, make_chunked_factory,
        )
        cpu, dev = sessions
        # stream only `sales` (the EXISTS set in this query)
        sess = Session(dev.catalog,
                       make_chunked_factory(stream_bytes=2000,
                                            chunk_rows=64))
        for t in dev.tables.values():
            sess.register_table(t)
        sql = ("select o_cat, count(*) c from other where not exists "
               "(select 1 from sales where s_store = o_store) "
               "group by o_cat order by o_cat")
        assert_frames_close(sess.sql(sql).to_pandas(),
                            cpu.sql(sql).to_pandas(), "q22-shape")
        ex = sess._executor_factory(sess.tables)
        assert not any(isinstance(s, _PartialAggExecutor)
                       for s in ex._reduced.values())

    def test_partial_agg_distinct_falls_back(self, chunked_pa):
        """count(distinct) cannot merge from partials — the plan must
        fall back to the full-upload phase B and still be correct."""
        cpu, sess = chunked_pa
        sql = ("select s_cat, count(distinct s_store) d from sales "
               "group by s_cat order by s_cat")
        assert_frames_close(sess.sql(sql).to_pandas(),
                            cpu.sql(sql).to_pandas(), "distinct-fallback")

    def test_survivor_cache_shared_across_plans(self, chunked):
        _cpu, sess = chunked
        ex = sess._executor_factory(sess.tables)
        before = len(ex._survivor_cache)
        # same table + same pushed-down filters -> same reduced table
        sess.sql("select count(*) c from sales where s_day between 5 and 12")
        sess.sql("select max(s_day) m from sales where s_day between 5 and 12")
        after = len(ex._survivor_cache)
        assert after <= before + 1


def test_make_session_stream_bytes_selects_chunked():
    """engine.stream_bytes > 0: the cost model places any plan whose
    widest scanned table exceeds the threshold on the out-of-core
    executor — a per-query scheduling decision now, not a stream-wide
    factory choice (engine/scheduler.py)."""
    from nds_tpu.engine.chunked_exec import ChunkedExecutor
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    from nds_tpu.nds.power import SUITE
    from nds_tpu.datagen import tpcds
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds.schema import get_schemas
    cfg = EngineConfig(overrides={"engine.backend": "tpu",
                                  "engine.stream_bytes": "1024",
                                  "engine.chunk_rows": "128"})
    sess = power_core.make_session(SUITE, cfg)
    pipe = sess._executor_factory(sess.tables)
    assert pipe.stream_bytes == 1024 and pipe.chunk_rows == 128
    schemas = get_schemas()
    sess.register_table(from_arrays("date_dim", schemas["date_dim"],
                                    tpcds.gen_table("date_dim", 0.01)))
    sess.sql("select count(*) c from date_dim")
    assert pipe.last_schedule["placement"] == "chunked"
    assert "table-exceeds-stream-bytes" in pipe.last_schedule["reason"]
    ex = pipe._executor("chunked")
    assert isinstance(ex, ChunkedExecutor)
    assert ex.stream_bytes == 1024 and ex.chunk_rows == 128


def test_device_result_compaction(sessions):
    """Large-capacity results compact on device before the host
    transfer (COMPACT_MIN_ROWS); forced low here — results must be
    identical to the uncompacted path."""
    from nds_tpu.engine.device_exec import DeviceExecutor

    cpu, dev = sessions

    class SmallCompact(DeviceExecutor):
        COMPACT_MIN_ROWS = 2

    ex_holder = [None]

    def factory(tables):
        if ex_holder[0] is None or ex_holder[0].tables is not tables:
            ex_holder[0] = SmallCompact(tables)
        return ex_holder[0]

    sess = Session(dev.catalog, factory)
    for t in dev.tables.values():
        sess.register_table(t)
    # string-dictionary, decimal, float, and int outputs all travel
    # the compacted transfer (threshold 2 engages every multi-row
    # capacity, including the G=4 group-by)
    for sql in [
        "select s_cat, sum(s_price) t from sales where s_qty > 25 "
        "group by s_cat order by s_cat",
        "select s_cat, avg(s_qty) a from sales group by s_cat "
        "order by s_cat",
        "select s_id, s_qty from sales where s_qty > 45 order by s_id",
    ]:
        assert_frames_close(sess.sql(sql).to_pandas(),
                            cpu.sql(sql).to_pandas(), sql[:40])


def test_filtered_scan_reduction(sessions):
    """Survivor reduction: a filtered scan compiles at reduced
    power-of-two capacity (the build-side shrink that makes the NDS
    gather joins chip-side wins); results must be identical, and the
    reduction must actually engage (not silently fall back)."""
    from nds_tpu.engine.device_exec import DeviceExecutor, _ReducedScan

    cpu, _dev = sessions

    class SmallReduce(DeviceExecutor):
        REDUCE_MIN_ROWS = 1

    ex_holder = [None]

    def factory(tables):
        if ex_holder[0] is None or ex_holder[0].tables is not tables:
            ex_holder[0] = SmallReduce(tables)
        return ex_holder[0]

    sess = Session(cpu.catalog, factory)
    for t in cpu.tables.values():
        sess.register_table(t)
    for sql in [
        # scan filter + aggregate: selective (s_qty > 45 keeps ~8%)
        "select s_cat, count(*) c from sales where s_qty > 45 "
        "group by s_cat order by s_cat",
        # reduced build side feeding a join
        "select s.s_cat, sum(s.s_qty) q from sales s, other o "
        "where s.s_cat = o.o_cat and s.s_store = o.o_store "
        "and s.s_qty > 40 group by s.s_cat order by s.s_cat",
        # string predicate (host dictionary eval) + null-valid column
        "select count(*) c, sum(s_price) p from sales "
        "where s_cat like 'a%' and s_qty is not null",
    ]:
        assert_frames_close(sess.sql(sql).to_pandas(),
                            cpu.sql(sql).to_pandas(), sql[:40])
    ex = ex_holder[0]
    reduced = [v for v in ex._scan_views.values()
               if isinstance(v, _ReducedScan)]
    assert reduced, "no scan was reduced — the shrink never engaged"
    for rv in reduced:
        full = ex.tables[rv.table].nrows
        assert rv.nrows < full
        assert rv.capacity & (rv.capacity - 1) == 0  # pow2 padding


def test_scan_reduction_survives_dml(sessions):
    """After an INSERT the session invalidates the executor; the fresh
    executor re-derives survivor sets from the NEW table contents."""
    from nds_tpu.engine.device_exec import DeviceExecutor

    cpu, _dev = sessions

    class SmallReduce(DeviceExecutor):
        REDUCE_MIN_ROWS = 1

    holder: dict = {}

    def factory(tables):
        ex = holder.get("ex")
        if ex is None or ex.tables is not tables:
            ex = SmallReduce(tables)
            holder["ex"] = ex
        return ex

    factory.invalidate = holder.clear

    cpu2 = Session(cpu.catalog, None)
    sess = Session(cpu.catalog, factory)
    for t in cpu.tables.values():
        sess.register_table(t)
        cpu2.register_table(t)
    q = ("select count(*) c from sales where s_qty > 45")
    ins = ("insert into sales select s_id + 10000, s_cat, s_store, "
           "49, s_price, s_day from sales where s_qty > 45")
    assert_frames_close(sess.sql(q).to_pandas(),
                        cpu2.sql(q).to_pandas(), "pre-dml")
    sess.sql(ins)
    cpu2.sql(ins)
    assert_frames_close(sess.sql(q).to_pandas(),
                        cpu2.sql(q).to_pandas(), "post-dml")


def test_engine_timings_carry_roofline(sessions):
    """Per-query bytes_scanned + achieved scan_gbps (the memory-roofline
    denominator the reference leaves to the Spark UI) must reach
    last_timings and survive into engineTimings JSON summaries."""
    _cpu, dev = sessions
    dev.sql("select count(*) c from sales where s_qty > 10")
    ex = dev._executor_factory(dev.tables)
    t = ex.last_timings
    assert t.get("bytes_scanned", 0) > 0
    assert t.get("scan_gbps", 0) > 0
