"""Static-analysis layer tests: plan verifier + ndslint + tier-1 gates.

Three layers, mirroring the subsystem (nds_tpu/analysis/):

- negative plan-verifier tests build deliberately malformed plans with
  raw constructors and assert each invariant class trips;
- lint-rule tests run every NDS1xx rule against small fixture snippets,
  violating and waived;
- gate tests execute tools/static_checks.py end-to-end and
  tools/ndsverify.py over all 103 NDS + 22 NDS-H statements, asserting
  the tree itself stays clean (the tier-1 contract from ISSUE 2).
"""

import pathlib
import subprocess
import sys

import numpy as np
import pytest

from nds_tpu.analysis import lint_rules, plan_verify
from nds_tpu.analysis.plan_verify import (
    PlanVerifyError, check_exchange_invariants, verify,
)
from nds_tpu.engine.session import Session
from nds_tpu.engine.types import (
    FLOAT64, INT32, INT64, STRING, Schema,
)
from nds_tpu.io.host_table import from_arrays
from nds_tpu.sql import ir
from nds_tpu.sql import plan as P
from nds_tpu.sql.planner import CatalogInfo

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


def _scan(name="t", binding="t", cols=(("a", INT32), ("b", INT64))):
    return P.Scan(name, binding, [(n, d) for n, d in cols])


def _rules(violations):
    return {v.rule for v in violations}


# ------------------------------------------------------- plan verifier

def test_valid_plan_is_clean():
    scan = _scan()
    proj = P.Project(scan, [("x", ir.ColRef("t", "a", INT32))], "p")
    assert verify(P.PlannedQuery(proj, [], ["x"])) == []


def test_dangling_colref():
    scan = _scan()
    proj = P.Project(scan, [("x", ir.ColRef("ghost", "a", INT32))], "p")
    assert "colref-unresolved" in _rules(
        verify(P.PlannedQuery(proj, [], ["x"])))


def test_colref_dtype_mismatch():
    scan = _scan()
    proj = P.Project(scan, [("x", ir.ColRef("t", "a", INT64))], "p")
    assert "colref-dtype" in _rules(
        verify(P.PlannedQuery(proj, [], ["x"])))


def test_mismatched_join_key_dtypes():
    s1 = _scan("t1", "t1", (("k", INT32),))
    s2 = _scan("t2", "t2", (("s", STRING),))
    j = P.Join("inner", s1, s2,
               [ir.ColRef("t1", "k", INT32)],
               [ir.ColRef("t2", "s", STRING)],
               None, False, output=list(s1.output), binding="t1")
    assert "join-key-dtype" in _rules(
        verify(P.PlannedQuery(j, [], ["k"])))


def test_join_key_arity_mismatch():
    s1 = _scan("t1", "t1", (("k", INT32),))
    s2 = _scan("t2", "t2", (("k", INT32),))
    j = P.SemiJoin(s1, s2, [ir.ColRef("t1", "k", INT32)], [], None)
    assert "join-key-arity" in _rules(
        verify(P.PlannedQuery(j, [], ["k"])))


def test_join_kernel_choice_invariants():
    # unknown kernel name fails; direct/matmul need a unique build;
    # partitioned only lowers the M:N inner expansion
    # (engine/kernels.py catalog; the planner's annotate() can only
    # stamp names the trace can lower)
    def _join(kernel, unique=True, kind="inner"):
        s1 = _scan("t1", "t1", (("k", INT32),))
        s2 = _scan("t2", "t2", (("k", INT32),))
        return P.Join(kind, s1, s2,
                      [ir.ColRef("t1", "k", INT32)],
                      [ir.ColRef("t2", "k", INT32)],
                      None, unique, output=list(s1.output),
                      binding="t1", kernel=kernel)

    assert "kernel-unknown" in _rules(verify(
        P.PlannedQuery(_join("warp9"), [], ["k"])))
    assert "kernel-shape" in _rules(verify(
        P.PlannedQuery(_join("direct", unique=False), [], ["k"])))
    assert "kernel-shape" in _rules(verify(
        P.PlannedQuery(_join("partitioned", unique=True), [], ["k"])))
    assert "kernel-shape" in _rules(verify(P.PlannedQuery(
        _join("partitioned", unique=False, kind="left"), [], ["k"])))
    # the legal shapes stay clean
    assert verify(P.PlannedQuery(_join("direct"), [], ["k"])) == []
    assert verify(P.PlannedQuery(
        _join("partitioned", unique=False), [], ["k"])) == []


def test_semi_and_agg_kernel_choice_invariants():
    s1 = _scan("t1", "t1", (("k", INT32),))
    s2 = _scan("t2", "t2", (("k", INT32),))
    sj = P.SemiJoin(s1, s2, [ir.ColRef("t1", "k", INT32)],
                    [ir.ColRef("t2", "k", INT32)], None,
                    kernel="holodeck")
    assert "kernel-unknown" in _rules(
        verify(P.PlannedQuery(sj, [], ["k"])))
    agg = P.Aggregate(_scan(), [("g", ir.ColRef("t", "a", INT32))],
                      [], binding="a", kernel="abacus")
    assert "kernel-unknown" in _rules(
        verify(P.PlannedQuery(agg, [], ["g"])))


def test_out_of_range_aggref_flags():
    # the planner remaps every AggRef onto agg-output ColRefs; one
    # surviving (here with an absurd index) must trip the verifier
    scan = _scan()
    proj = P.Project(scan, [("x", ir.AggRef(99, INT64))], "p")
    assert "ref-unresolved" in _rules(
        verify(P.PlannedQuery(proj, [], ["x"])))


def test_scalarref_out_of_range():
    scan = _scan()
    proj = P.Project(scan, [("x", ir.ScalarRef(3, INT64))], "p")
    assert "scalarref-range" in _rules(
        verify(P.PlannedQuery(proj, [], ["x"])))


def test_arith_dtype_propagation():
    scan = _scan()
    bad = ir.Arith("+", ir.ColRef("t", "a", INT32),
                   ir.Lit(1, INT32), FLOAT64)  # int32+int32 is int32
    proj = P.Project(scan, [("x", bad)], "p")
    assert "arith-dtype" in _rules(
        verify(P.PlannedQuery(proj, [], ["x"])))


def test_agg_dtype_propagation():
    scan = _scan()
    agg = P.Aggregate(scan, [], [("s", P.AggSpec(
        "sum", ir.ColRef("t", "a", INT32), False, INT32))], "g")
    assert "agg-dtype" in _rules(  # sum(int32) widens to int64
        verify(P.PlannedQuery(agg, [], ["s"])))


def test_negative_limit():
    assert "limit-count" in _rules(
        verify(P.PlannedQuery(P.Limit(_scan(), -1), [], ["a", "b"])))


def test_setop_arity_mismatch():
    l = P.Project(_scan(), [("x", ir.ColRef("t", "a", INT32)),
                            ("y", ir.ColRef("t", "b", INT64))], "pl")
    r = P.Project(_scan(), [("x", ir.ColRef("t", "a", INT32))], "pr")
    u = P.SetOp("union all", l, r)
    assert "setop-arity" in _rules(verify(P.PlannedQuery(u, [], ["x", "y"])))


def test_setop_dtype_mismatch():
    l = P.Project(_scan(), [("x", ir.ColRef("t", "a", INT32))], "pl")
    r = P.Project(_scan(), [("x", ir.ColRef("t", "b", INT64))], "pr")
    bad = P.Project(_scan(), [("x", ir.Lit("s", STRING))], "ps")
    u = P.SetOp("union all", l, bad)
    assert "setop-dtype" in _rules(verify(P.PlannedQuery(u, [], ["x"])))
    ok = P.SetOp("union all", l, r)  # int widths may differ
    assert "setop-dtype" not in _rules(verify(P.PlannedQuery(ok, [], ["x"])))


def test_stagedscan_mangle_and_registration():
    temp = P.Scan("__stage_1", "__t", [("t__a", INT32)])
    good = P.StagedScan(temp, [("t", "a", "t__a", INT32)], "t",
                        [("a", INT32)])
    pq = P.PlannedQuery(P.Filter(good, ir.Cmp(
        "=", ir.ColRef("t", "a", INT32), ir.Lit(1, INT32))), [], ["a"])
    assert verify(pq) == []
    # unregistered temp only flags when a table registry is supplied
    # (and the backing Scan independently flags as unregistered too)
    got = _rules(verify(pq, tables={}))
    assert "staged-unregistered" in got and "scan-unregistered" in got
    bad = P.StagedScan(temp, [("t", "a", "WRONG", INT32)], "t",
                       [("a", INT32)])
    assert "staged-mangle" in _rules(
        verify(P.PlannedQuery(bad, [], ["a"])))


def test_exchange_invariants():
    assert check_exchange_invariants(1000, 8, 2.0) == []
    assert {v.rule for v in check_exchange_invariants(1000, 8, 0.5)} == {
        "exchange-slack"}
    assert "exchange-mesh" in {
        v.rule for v in check_exchange_invariants(1000, 0, 2.0)}


def test_assert_valid_raises_with_context():
    scan = _scan()
    proj = P.Project(scan, [("x", ir.ColRef("ghost", "a", INT32))], "p")
    with pytest.raises(PlanVerifyError, match="colref-unresolved"):
        plan_verify.assert_valid(P.PlannedQuery(proj, [], ["x"]),
                                 label="unit")


# -------------------------------------------- session + executor gates

def _tiny_session():
    sch = Schema.of(("k", INT32, False), ("x", INT32, False))
    cat = CatalogInfo({"t": sch}, {"t": ("k",)}, {"t": 10.0})
    s = Session(cat)
    s.register_table(from_arrays(
        "t", sch, {"k": np.array([1, 2], np.int32),
                   "x": np.array([10, 20], np.int32)}))
    return s


def test_session_plan_verifies_under_env(monkeypatch):
    s = _tiny_session()
    # a structurally broken view body: resolvable by the planner (its
    # output list is fine) but with a dangling ColRef inside
    s.views["broken_v"] = P.Project(
        P.Scan("t", "b", []), [("x", ir.ColRef("ghost", "c", INT32))],
        "pv")
    monkeypatch.setenv(plan_verify.ENV_FLAG, "1")
    with pytest.raises(PlanVerifyError, match="colref-unresolved"):
        s.plan("select x from broken_v")
    monkeypatch.setenv(plan_verify.ENV_FLAG, "0")
    assert isinstance(s.plan("select x from broken_v"), P.PlannedQuery)


def test_duplicate_output_names_stay_positional():
    # q64 regression: unaliased same-named columns from two bindings
    # must keep their own values (the planner dedupes internal names;
    # display names stay as written)
    s = _tiny_session()
    r = s.sql("select a.x, b.x from t a, t b "
              "where a.k = 1 and b.k = 2 and a.k < b.k")
    assert r.names == ["x", "x"]
    assert r.to_pandas().values.tolist() == [[10, 20]]


def test_register_staged_hashes_full_content():
    # ADVICE r5: a same-shape change PAST the old 16Ki prefix must
    # invalidate the staged fingerprint (stale device buffers otherwise)
    from nds_tpu.engine.device_exec import DeviceExecutor
    n = (1 << 14) + 8
    sch = Schema.of(("c", INT64, False))
    a1 = np.zeros(n, np.int64)
    ex = DeviceExecutor({})
    ex._register_staged("__stage_t", from_arrays("__stage_t", sch,
                                                 {"c": a1}))
    fp1 = ex._stage_fps["__stage_t"]
    a2 = a1.copy()
    a2[-1] = 7
    t2 = from_arrays("__stage_t", sch, {"c": a2})
    ex._register_staged("__stage_t", t2)
    assert ex._stage_fps["__stage_t"] != fp1
    assert ex.tables["__stage_t"] is t2


# ------------------------------------------------------------- ndslint

def _lint(src, path="nds_tpu/engine/fixture.py", enabled=None):
    res = lint_rules.lint_sources({path: src}, enabled=enabled)
    return res


def test_rule_id_keyed_cache():
    res = _lint("def f(c, x):\n    c[id(x)] = 1\n", enabled={"NDS101"})
    assert _rules(res.violations) == {"NDS101"}
    res = _lint("def f(c, x):\n    nid = id(x)\n    c[nid] = 1\n",
                enabled={"NDS101"})
    assert _rules(res.violations) == {"NDS101"}
    res = _lint("def f(c, x):\n    c.setdefault(id(x), [])\n",
                enabled={"NDS101"})
    assert _rules(res.violations) == {"NDS101"}
    waived = ("def f(c, x):\n"
              "    # ndslint: waive[NDS101] -- value pins x\n"
              "    c[id(x)] = (x, 1)\n")
    res = _lint(waived, enabled={"NDS101"})
    assert res.violations == [] and len(res.waived) == 1
    assert res.waived[0].waiver_note == "value pins x"


def test_rule_raw_timing_scoped_to_engine():
    src = "import time\n\ndef f():\n    return time.perf_counter()\n"
    assert _rules(_lint(src, enabled={"NDS102"}).violations) == {"NDS102"}
    # same source outside engine//parallel/ is fine
    assert _lint(src, path="nds_tpu/utils/fixture.py",
                 enabled={"NDS102"}).violations == []


def test_rule_unsynced_device_timing():
    src = ("import time\n"
           "import jax.numpy as jnp\n\n"
           "def f(x):\n"
           "    t0 = time.perf_counter()\n"
           "    y = jnp.sum(x)\n"
           "    return (time.perf_counter() - t0), y\n")
    assert "NDS103" in _rules(_lint(src, enabled={"NDS103"}).violations)
    synced = src.replace("return (time.perf_counter() - t0), y",
                         "y.block_until_ready()\n"
                         "    return (time.perf_counter() - t0), y")
    assert _lint(synced, enabled={"NDS103"}).violations == []


def test_rule_prefix_hash():
    src = ("def f(h, arr):\n"
           "    h.update(arr[: 1 << 14].tobytes())\n")
    assert _rules(_lint(src, enabled={"NDS104"}).violations) == {"NDS104"}
    full = "def f(h, arr):\n    h.update(arr.tobytes())\n"
    assert _lint(full, enabled={"NDS104"}).violations == []


def test_rule_dead_dataclass_field():
    src = ("from dataclasses import dataclass\n\n"
           "@dataclass\n"
           "class C:\n"
           "    used: int = 0\n"
           "    zz_never_read_zz: int = 0\n\n"
           "def f(c):\n"
           "    return c.used\n")
    res = _lint(src, enabled={"NDS105"})
    assert [v.rule for v in res.violations] == ["NDS105"]
    assert "zz_never_read_zz" in res.violations[0].msg


def test_rule_mutable_default_and_bare_except():
    src = ("def f(a=[]):\n"
           "    try:\n"
           "        return a\n"
           "    except:\n"
           "        pass\n")
    assert _rules(_lint(src, enabled={"NDS106", "NDS107"}).violations) \
        == {"NDS106", "NDS107"}


def test_rule_direct_executor_construction():
    src = ("def f(tables):\n"
           "    from nds_tpu.engine.device_exec import DeviceExecutor\n"
           "    return DeviceExecutor(tables)\n")
    assert _rules(_lint(src, enabled={"NDS110"}).violations) == {"NDS110"}
    # attribute form flags too
    attr = ("from nds_tpu.engine import cpu_exec as cx\n\n"
            "def f(tables):\n"
            "    return cx.CpuExecutor(tables)\n")
    assert _rules(_lint(attr, enabled={"NDS110"}).violations) == {"NDS110"}
    # the scheduler itself is the allowed construction point
    assert _lint(src, path="nds_tpu/engine/scheduler.py",
                 enabled={"NDS110"}).violations == []
    # an executor's own module constructs freely (factories, subclass
    # helpers)
    assert _lint(src, path="nds_tpu/engine/device_exec.py",
                 enabled={"NDS110"}).violations == []
    # ...but only for ITS executor
    assert _rules(_lint(attr, path="nds_tpu/engine/device_exec.py",
                        enabled={"NDS110"}).violations) == {"NDS110"}
    # waivable like every rule
    waived = ("def f(tables):\n"
              "    # ndslint: waive[NDS110] -- bounds probe only\n"
              "    return DeviceExecutor(tables)\n")
    res = _lint(waived, enabled={"NDS110"})
    assert res.violations == [] and len(res.waived) == 1


def test_rule_uncached_compile():
    # jax.jit inside engine/ flags
    src = ("import jax\n\n"
           "def f(fn, bufs):\n"
           "    return jax.jit(fn)\n")
    assert _rules(_lint(src, enabled={"NDS111"}).violations) \
        == {"NDS111"}
    # .lower(args) AOT chain flags
    aot = ("def f(jitted, bufs):\n"
           "    return jitted.lower(bufs).compile()\n")
    assert _rules(_lint(aot, path="nds_tpu/parallel/fixture.py",
                        enabled={"NDS111"}).violations) == {"NDS111"}
    # string lowering is NOT an AOT chain: no-arg method, np.char
    # module form, str builtin
    clean = ("import numpy as np\n\n"
             "def f(s, arr):\n"
             "    a = s.lower()\n"
             "    b = np.char.lower(arr)\n"
             "    return a, b, str.lower(s)\n")
    assert _lint(clean, enabled={"NDS111"}).violations == []
    # out of scope outside engine//parallel/ (the cache module is the
    # one compile site)
    assert _lint(aot, path="nds_tpu/cache/aot.py",
                 enabled={"NDS111"}).violations == []
    # waivable for build-only jit sites
    waived = ("import jax\n\n"
              "def f(fn):\n"
              "    # ndslint: waive[NDS111] -- builds the traced callable only\n"
              "    return jax.jit(fn)\n")
    res = _lint(waived, enabled={"NDS111"})
    assert res.violations == [] and len(res.waived) == 1


def test_rule_int64_emulation_hazard():
    # argsort/sort/searchsorted without an int32 mention flag in
    # engine//parallel/
    for call in ("jnp.argsort(dest)",
                 "jnp.sort(keys)",
                 "jnp.searchsorted(ks, q, side='left')"):
        src = f"def f(jnp, dest, keys, ks, q):\n    return {call}\n"
        assert _rules(_lint(src, enabled={"NDS112"}).violations) \
            == {"NDS112"}, call
    # an explicit int32 in the CALL is the handled-width signal
    clean = ("def f(jnp, ks, q, n):\n"
             "    a = jnp.searchsorted(ks, q.astype(jnp.int32))\n"
             "    b = jnp.sort(ks.astype(jnp.int32))\n"
             "    return a, b\n")
    assert _lint(clean, enabled={"NDS112"}).violations == []
    # out of scope outside engine//parallel/
    src = "def f(jnp, x):\n    return jnp.sort(x)\n"
    assert _lint(src, path="nds_tpu/obs/fixture.py",
                 enabled={"NDS112"}).violations == []
    # waivable where the 64-bit operand is genuinely required
    waived = ("def f(jnp, x):\n"
              "    # ndslint: waive[NDS112] -- packed key needs 64 bits\n"
              "    return jnp.sort(x)\n")
    res = _lint(waived, enabled={"NDS112"})
    assert res.violations == [] and len(res.waived) == 1


def test_rule_direct_profiler():
    # jax.profiler.start_trace outside obs/profile.py flags, both
    # spellings
    for call in ("jax.profiler.start_trace('/tmp/x')",
                 "profiler.start_trace(d)"):
        src = f"def f(jax, profiler, d):\n    {call}\n"
        assert _rules(_lint(src, enabled={"NDS113"}).violations) \
            == {"NDS113"}, call
    # the profile module itself is the one legitimate owner
    src = "def f(jax, d):\n    jax.profiler.start_trace(d)\n"
    assert _lint(src, path="nds_tpu/obs/profile.py",
                 enabled={"NDS113"}).violations == []
    # stop_trace / unrelated start_trace attrs don't match
    clean = ("def f(jax, server):\n"
             "    jax.profiler.stop_trace()\n"
             "    server.start_trace('/x')\n")
    assert _lint(clean, enabled={"NDS113"}).violations == []
    # the production tree holds the invariant: the only start_trace
    # sites under nds_tpu/ + tools/ live in obs/profile.py
    import pathlib
    repo = pathlib.Path(__file__).resolve().parent.parent
    offenders = []
    for root in ("nds_tpu", "tools"):
        for p in (repo / root).rglob("*.py"):
            if "start_trace" in p.read_text() \
                    and not str(p).endswith("obs/profile.py"):
                res = lint_rules.lint_sources(
                    {str(p.relative_to(repo)): p.read_text()},
                    enabled={"NDS113"})
                offenders += res.violations
    assert offenders == [], offenders


def test_waiver_requires_justification_and_use():
    src = ("def f(a=[]):  # ndslint: waive[NDS106]\n"
           "    return a\n")
    res = _lint(src, enabled={"NDS106"})
    # malformed waiver is an error AND the violation stays unwaived
    assert any(v.rule == "NDS100" for v in res.errors)
    assert _rules(res.violations) == {"NDS106"}
    stale = "def f(a):\n    # ndslint: waive[NDS106] -- nothing here\n    return a\n"
    res = _lint(stale, enabled={"NDS106"})
    assert any("matches no violation" in v.msg for v in res.errors)


# --------------------------------------------------------- tier-1 gates

def test_ndsverify_all_125_statements_clean(capsys):
    import ndsverify
    assert ndsverify.main(["--suite", "all"]) == 0
    out = capsys.readouterr().out
    assert "103 nds" in out and "22 nds_h" in out


def test_static_checks_end_to_end():
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "static_checks.py")],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "STATIC CHECKS OK" in r.stdout


def test_rule_unchained_signal_handler():
    # installing a real handler with no getsignal in scope flags
    src = ("import signal\n"
           "def install(h):\n"
           "    signal.signal(signal.SIGTERM, h)\n")
    assert _rules(_lint(src, path="nds_tpu/obs/fixture.py",
                        enabled={"NDS114"}).violations) == {"NDS114"}
    # capturing the previous handler first (the chain pattern) is clean
    chained = ("import signal\n"
               "def install(h):\n"
               "    prev = signal.getsignal(signal.SIGTERM)\n"
               "    signal.signal(signal.SIGTERM, h)\n"
               "    return prev\n")
    assert _lint(chained, path="nds_tpu/obs/fixture.py",
                 enabled={"NDS114"}).violations == []
    # an ancestor closure that captured prev covers nested installs
    nested = ("import signal\n"
              "def install(h):\n"
              "    prev = signal.getsignal(signal.SIGTERM)\n"
              "    def _on(s, f):\n"
              "        signal.signal(signal.SIGTERM, h)\n"
              "    signal.signal(signal.SIGTERM, _on)\n")
    assert _lint(nested, path="nds_tpu/obs/fixture.py",
                 enabled={"NDS114"}).violations == []
    # restoring the default/ignore disposition is not a chain hazard
    restore = ("import signal\n"
               "def reraise():\n"
               "    signal.signal(signal.SIGTERM, signal.SIG_DFL)\n"
               "    signal.signal(signal.SIGINT, signal.SIG_IGN)\n")
    assert _lint(restore, path="nds_tpu/obs/fixture.py",
                 enabled={"NDS114"}).violations == []
    # outside nds_tpu/ the rule does not apply
    assert _lint(src, path="tools/fixture.py",
                 enabled={"NDS114"}).violations == []
    # waivable with justification
    waived = ("import signal\n"
              "def install(h):\n"
              "    # ndslint: waive[NDS114] -- test fixture owns it\n"
              "    signal.signal(signal.SIGTERM, h)\n")
    res = _lint(waived, path="nds_tpu/obs/fixture.py",
                enabled={"NDS114"})
    assert res.violations == [] and len(res.waived) == 1
    # the production tree holds the invariant: every signal.signal
    # site under nds_tpu/ chains (obs/fleet.py, resilience/drain.py)
    import pathlib
    repo = pathlib.Path(__file__).resolve().parent.parent
    offenders = []
    for p in (repo / "nds_tpu").rglob("*.py"):
        if "signal.signal(" in p.read_text():
            res = lint_rules.lint_sources(
                {str(p.relative_to(repo)): p.read_text()},
                enabled={"NDS114"})
            offenders += res.violations
    assert offenders == [], offenders


def test_nds114_in_default_rules():
    assert any(r.id == "NDS114"
               for r in lint_rules.default_rules())


def test_rule_unjournaled_mutation():
    # a raw subscript store into a .tables catalog flags
    src = ("def swap(sess, name, t):\n"
           "    sess.tables[name] = t\n")
    assert _rules(_lint(src, path="nds_tpu/obs/fixture.py",
                        enabled={"NDS119"}).violations) == {"NDS119"}
    # so do del and the dict mutator methods on .tables/.columns
    extra = ("def drop(sess, store, name):\n"
             "    del sess.tables[name]\n"
             "    store.columns.pop(name, None)\n"
             "    sess.tables.update({name: None})\n"
             "    sess.tables.clear()\n")
    res = _lint(extra, path="nds_tpu/obs/fixture.py",
                enabled={"NDS119"})
    assert len(res.violations) == 4
    # reads and mutation of unrelated attributes are clean
    clean = ("def peek(sess, name):\n"
             "    t = sess.tables[name]\n"
             "    sess.caches[name] = t\n"
             "    return sess.tables.get(name)\n")
    assert _lint(clean, path="nds_tpu/obs/fixture.py",
                 enabled={"NDS119"}).violations == []
    # the journaled machinery itself is the blessed mutation path
    for allowed in ("nds_tpu/engine/session.py",
                    "nds_tpu/engine/dml.py",
                    "nds_tpu/columnar/delta.py",
                    "nds_tpu/io/host_table.py"):
        assert _lint(src, path=allowed,
                     enabled={"NDS119"}).violations == []
    # outside nds_tpu/ the rule does not apply
    assert _lint(src, path="tools/fixture.py",
                 enabled={"NDS119"}).violations == []
    # waivable with justification
    waived = ("def swap(sess, name, t):\n"
              "    # ndslint: waive[NDS119] -- fixture-local dict\n"
              "    sess.tables[name] = t\n")
    res = _lint(waived, path="nds_tpu/obs/fixture.py",
                enabled={"NDS119"})
    assert res.violations == [] and len(res.waived) == 1
    # the production tree holds the invariant: every catalog write
    # under nds_tpu/ is journaled machinery or an audited waiver
    # (device_exec staged temps, plan_verify cost accumulator)
    import pathlib
    repo = pathlib.Path(__file__).resolve().parent.parent
    offenders = []
    for p in (repo / "nds_tpu").rglob("*.py"):
        txt = p.read_text()
        if ".tables[" in txt or ".columns[" in txt \
                or ".tables." in txt or ".columns." in txt:
            res = lint_rules.lint_sources(
                {str(p.relative_to(repo)): txt},
                enabled={"NDS119"})
            offenders += res.violations
    assert offenders == [], offenders


def test_nds119_in_default_rules():
    assert any(r.id == "NDS119"
               for r in lint_rules.default_rules())
