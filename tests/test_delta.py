"""Delta-layer crash-window tests (nds_tpu/columnar/delta.py +
io/snapshots.py): append/delete semantics over synthetic tables,
segment-granular content digests, the torn-commit window (delta files
on disk, snapshot manifest never appended -> a fresh reader serves the
prior version, and a recovery re-commit makes the mutation visible
without rewriting files), digest verification on load (every corruption
raises CorruptArtifact deterministically), rollback-to-baseline, and
the validate summary patch contract.

The full maintenance pipeline over a generated warehouse lives in
test_maintenance.py and tools/maint_check.py (SIGKILL chaos + CPU
oracle); this file pins the storage-layer invariants those builds on,
at synthetic-table speed.
"""

import json
import os

import numpy as np
import pytest

from nds_tpu.cache import fingerprint
from nds_tpu.columnar import delta
from nds_tpu.engine.types import INT32, INT64, Schema, varchar
from nds_tpu.io import csv_io, integrity
from nds_tpu.io.host_table import from_arrays
from nds_tpu.io.snapshots import SnapshotLog
from nds_tpu.nds import validate

SCHEMA = Schema.of(
    ("d_id", INT64, False),
    ("d_qty", INT32, True),        # carries a null mask
    ("d_tag", varchar(8), True))   # dict-encoded, later segs grow it

BASE_ROWS = 20


def _tbl(name="dtab", start=0, n=BASE_ROWS, tag_mod=5):
    rng = np.random.default_rng(1000 + start + n)
    ids = np.arange(start, start + n, dtype=np.int64)
    qty = rng.integers(0, 100, n).astype(np.int32)
    tags = np.array([f"tag{i % tag_mod}" for i in range(start, start + n)],
                    dtype=object)
    return from_arrays(name, SCHEMA, {
        "d_id": ids,
        "d_qty": qty, "d_qty#null": rng.random(n) > 0.2,
        "d_tag": tags, "d_tag#null": rng.random(n) > 0.1,
    })


def _mutate(table):
    """The canonical mutation both writer and reader must agree on:
    append 7 rows (3 of them with dictionary-new tags) then delete 5
    of the merged physical rows."""
    t2 = delta.append_segment(table, _tbl(start=100, n=7, tag_mod=9),
                              seg_id="seg-a")
    keep = np.ones(t2.nrows, dtype=bool)
    keep[[1, 3, 5, 21, 25]] = False
    return delta.apply_delete(t2, keep)


class TestDeltaUnits:
    def test_append_and_delete_semantics(self):
        t = _tbl()
        assert delta.state_of(t) is None
        assert delta.delta_report(t) is None
        assert delta.visible_rows(t) == BASE_ROWS
        t3 = _mutate(t)
        assert t3.nrows == BASE_ROWS + 7          # physical
        assert delta.visible_rows(t3) == BASE_ROWS + 7 - 5
        assert delta.segment_count(t3) == 1
        assert delta.delta_report(t3) == {
            "segments": 1, "appended_rows": 7, "masked_rows": 5}
        mask = delta.live_mask(t3)
        assert mask is not None and int(mask.sum()) == BASE_ROWS + 2
        # appended values land at the tail of the physical arrays
        tail = t3.columns["d_id"].values[BASE_ROWS:]
        np.testing.assert_array_equal(
            tail, np.arange(100, 107, dtype=np.int64))
        # physical() gathers the deleted rows out, once
        phys = delta.physical(t3)
        assert phys.nrows == BASE_ROWS + 2
        assert delta.physical(t3) is phys  # memoized
        assert 1 not in phys.columns["d_id"].values

    def test_delete_shares_column_objects(self):
        """apply_delete must not copy arrays: the device buffers and
        encoding memos hang off the column objects, and the whole point
        of the bitmask design is that a DELETE invalidates nothing."""
        t2 = delta.append_segment(_tbl(), _tbl(start=100, n=7),
                                  seg_id="s")
        keep = np.ones(t2.nrows, dtype=bool)
        keep[0] = False
        t3 = delta.apply_delete(t2, keep)
        for f in SCHEMA:
            assert t3.columns[f.name] is t2.columns[f.name]

    def test_stats_merge_exact_bounds(self):
        t3 = _mutate(_tbl())
        st = delta.state_of(t3)
        assert st.col_stats["d_id"]["lo"] == 0
        assert st.col_stats["d_id"]["hi"] == 106

    def test_content_digest_moves_and_is_pure(self):
        t = _tbl()
        d_base = fingerprint.table_digest(t)
        t2 = delta.append_segment(t, _tbl(start=100, n=7), seg_id="s")
        d_append = delta.state_of(t2).content_digest()
        keep = np.ones(t2.nrows, dtype=bool)
        keep[2] = False
        d_del = delta.state_of(
            delta.apply_delete(t2, keep)).content_digest()
        assert len({d_base, d_append, d_del}) == 3
        # pure function of the ops: replaying identical ops on an
        # identically-built base reproduces the digest exactly
        u2 = delta.append_segment(_tbl(), _tbl(start=100, n=7),
                                  seg_id="s")
        u3 = delta.apply_delete(u2, keep)
        assert delta.state_of(u3).content_digest() == d_del


# --------------------------------------------------------- persistence

def _seed_warehouse(tmp_path):
    """Baseline-only warehouse: one parquet file under <wh>/dtab/."""
    wh = str(tmp_path / "wh")
    tdir = os.path.join(wh, "dtab")
    os.makedirs(tdir)
    csv_io.write_table(_tbl(), os.path.join(tdir, "part-0.parquet"),
                       "parquet")
    return wh


def _load_current(wh):
    paths = SnapshotLog(wh).current(["dtab"])["dtab"]
    return paths, delta.load_versioned("dtab", SCHEMA, paths, "parquet")


def _persist_mutation(wh, commit):
    """Replay the canonical mutation against the warehouse's current
    version and persist it into _v1; append the snapshot manifest entry
    only when ``commit`` — False models the crash inside the torn
    window (files durable, manifest not)."""
    log = SnapshotLog(wh)
    _paths, base = _load_current(wh)
    t3 = _mutate(base)
    vdir = log.version_dir("dtab", 1)
    files = delta.persist_pending(t3, vdir, note="LF_TEST")
    assert files and os.path.basename(files[0]) == delta.OPS_NAME
    if commit:
        log.commit_delta(
            "dtab", [os.path.relpath(p, wh) for p in files],
            note="LF_TEST")
    return t3, files


class TestTornCommit:
    def test_torn_commit_serves_previous_version(self, tmp_path):
        wh = _seed_warehouse(tmp_path)
        _paths0, base0 = _load_current(wh)
        d0 = fingerprint.table_digest(base0)
        t3, files = _persist_mutation(wh, commit=False)
        # the delta artifacts are durable on disk...
        assert all(os.path.exists(p) for p in files)
        # ...but a fresh reader's manifest never references them: the
        # baseline walk skips _v* dirs and serves version 0 unchanged
        paths, reloaded = _load_current(wh)
        assert not delta.has_delta_paths(paths)
        assert reloaded.nrows == BASE_ROWS
        assert delta.visible_rows(reloaded) == BASE_ROWS
        assert fingerprint.table_digest(reloaded) == d0

    def test_recovery_commit_publishes_without_rewriting(self, tmp_path):
        wh = _seed_warehouse(tmp_path)
        t3, files = _persist_mutation(wh, commit=False)
        mtimes = {p: os.path.getmtime(p) for p in files}
        # recovery: resume finds the version dir complete and only
        # appends the manifest entry — the atomic commit point
        log = SnapshotLog(wh)
        assert not log.has_note("LF_TEST")
        log.commit_delta("dtab",
                         [os.path.relpath(p, wh) for p in files],
                         note="LF_TEST")
        assert SnapshotLog(wh).has_note("LF_TEST")
        paths, eff = _load_current(wh)
        assert delta.has_delta_paths(paths)
        assert delta.visible_rows(eff) == BASE_ROWS + 7 - 5
        assert (delta.state_of(eff).content_digest()
                == delta.state_of(t3).content_digest())
        np.testing.assert_array_equal(
            delta.physical(eff).columns["d_id"].values,
            delta.physical(t3).columns["d_id"].values)
        assert mtimes == {p: os.path.getmtime(p) for p in files}

    def test_committed_mutation_survives_reload(self, tmp_path):
        wh = _seed_warehouse(tmp_path)
        t3, _files = _persist_mutation(wh, commit=True)
        _paths, eff = _load_current(wh)
        assert delta.visible_rows(eff) == delta.visible_rows(t3)
        assert (delta.state_of(eff).content_digest()
                == delta.state_of(t3).content_digest())


class TestDigestVerification:
    """verify_digests is forced on under tests (conftest): every delta
    artifact re-hashes against the version dir's manifest on load, and
    the op list carries a CRC — each corruption class must raise
    CorruptArtifact, and deterministically (same answer on retry)."""

    def _committed(self, tmp_path):
        wh = _seed_warehouse(tmp_path)
        _persist_mutation(wh, commit=True)
        return wh, os.path.join(wh, "dtab", "_v1")

    def _assert_raises_twice(self, wh):
        for _ in range(2):
            paths = SnapshotLog(wh).current(["dtab"])["dtab"]
            with pytest.raises(integrity.CorruptArtifact):
                delta.load_versioned("dtab", SCHEMA, paths, "parquet")

    def test_flipped_byte_in_segment_file(self, tmp_path):
        wh, vdir = self._committed(tmp_path)
        seg = os.path.join(vdir, "delta-0.parquet")
        blob = bytearray(open(seg, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(seg, "wb").write(bytes(blob))
        self._assert_raises_twice(wh)

    def test_tampered_op_list_fails_crc(self, tmp_path):
        wh, vdir = self._committed(tmp_path)
        ops_path = os.path.join(vdir, delta.OPS_NAME)
        with open(ops_path) as f:
            doc = json.load(f)
        doc["note"] = "tampered"  # stale crc stamp
        with open(ops_path, "w") as f:
            json.dump(doc, f)
        self._assert_raises_twice(wh)

    def test_truncated_mask_detected(self, tmp_path):
        wh, vdir = self._committed(tmp_path)
        [mask] = [f for f in os.listdir(vdir) if f.endswith(".npz")]
        path = os.path.join(vdir, mask)
        blob = open(path, "rb").read()
        open(path, "wb").write(blob[:len(blob) // 2])
        self._assert_raises_twice(wh)


class TestRollback:
    def test_rollback_to_baseline_restores_bytes(self, tmp_path):
        wh = _seed_warehouse(tmp_path)
        _paths0, base0 = _load_current(wh)
        d0 = fingerprint.table_digest(base0)
        _persist_mutation(wh, commit=True)
        assert delta.has_delta_paths(
            SnapshotLog(wh).current(["dtab"])["dtab"])
        log = SnapshotLog(wh)
        assert log.rollback_to_timestamp(0.0) is None
        paths, reloaded = _load_current(wh)
        assert not delta.has_delta_paths(paths)
        assert fingerprint.table_digest(reloaded) == d0
        # and the persisted manifest agrees for the NEXT process too
        assert SnapshotLog(wh).entries == []


class TestValidateSummary:
    def test_update_summary_patches_status(self, tmp_path):
        folder = str(tmp_path / "json")
        os.makedirs(folder)
        for q in ("query7", "query96"):
            with open(os.path.join(folder, f"{q}.json"), "w") as f:
                json.dump({"query": q, "queryStatus": ["Completed"]}, f)
        with open(os.path.join(folder, "notes.json"), "w") as f:
            json.dump({"info": "no query key"}, f)
        validate.update_summary(folder, ["query7"])
        get = lambda q: json.load(  # noqa: E731
            open(os.path.join(folder, f"{q}.json")))
        assert get("query7")["queryValidationStatus"] == ["NotMatch"]
        assert get("query96")["queryValidationStatus"] == ["Match"]
        with open(os.path.join(folder, "notes.json")) as f:
            assert "queryValidationStatus" not in json.load(f)
