"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

This is the "multi-node without a cluster" tier (SURVEY.md §4): the
reference's analog is Spark local[*] mode (`shared/base.template:27`); ours
is XLA's host-platform device multiplexing, so every sharding/collective
path is exercised without TPU hardware.

Must run before any jax import — pytest imports conftest first.
"""

import os

# force, don't setdefault: the ambient environment may point JAX at a
# remote TPU tunnel (axon); tests must run on the local virtual CPU mesh
os.environ["JAX_PLATFORMS"] = "cpu"

# plan verification is ALWAYS on under tests (nds_tpu/analysis): every
# plan any test produces gets its structural invariants checked at
# planning time and again post-staging on the device path
os.environ["NDS_TPU_VERIFY_PLANS"] = "1"

# artifact digest verification likewise (nds_tpu/io/integrity.py):
# every warehouse/cache read a test performs re-hashes against its
# table manifest; files without a manifest load unverified, so
# fixtures predating manifests keep working
os.environ["NDS_TPU_VERIFY_DIGESTS"] = "1"

# runtime lock-order sanitizer (nds_tpu/analysis/locksan.py): every
# engine lock created in the test process (and in the fleet/soak/serve
# subprocesses, which inherit the env) is wrapped to record per-thread
# acquisition order — an inversion any test provokes prints loudly and
# fails the static_checks locksan gate. setdefault so NDS_TPU_LOCKSAN=0
# can opt a debugging session out.
os.environ.setdefault("NDS_TPU_LOCKSAN", "1")


def _jaxlib_knows(*flag_names: str) -> bool:
    """True when the installed jaxlib's binaries mention EVERY given
    XLA flag. XLA ABORTS the whole process on any unknown XLA_FLAGS
    entry (older jaxlibs predate the collective-timeout flags below,
    and the abort killed the entire pytest run at the first device
    use), so probe the shared objects for the flags' names before
    opting in. The grep verdict is cached in a tempdir marker keyed by
    the jaxlib version (the install cannot change mid-run), so the
    multi-hundred-MB scan runs once per install, not once per pytest
    session. Probe failure keeps the flags (the original behavior)."""
    import hashlib
    import importlib.util
    import pathlib
    import shlex
    import subprocess
    import tempfile
    try:
        import jaxlib
        spec = importlib.util.find_spec("jaxlib")
        root = list(spec.submodule_search_locations)[0]
        tag = hashlib.md5(
            "|".join((jaxlib.__version__, root) + flag_names).encode()
        ).hexdigest()[:12]
        cache = pathlib.Path(tempfile.gettempdir()) / (
            f"nds_tpu_xlaflag_probe_{tag}")
        if cache.exists():
            return cache.read_text() == "1"
        cmd = " && ".join(
            f"grep -rqs {shlex.quote(f)} {shlex.quote(root)}"
            for f in flag_names)
        ok = subprocess.run(["sh", "-c", cmd],
                            timeout=120).returncode == 0
        cache.write_text("1" if ok else "0")
        return ok
    except Exception:  # noqa: BLE001 - no grep/jaxlib layout surprises
        return True


flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "collective_call_terminate" not in flags and _jaxlib_knows(
        "xla_cpu_collective_call_warn_stuck_timeout_seconds",
        "xla_cpu_collective_call_terminate_timeout_seconds"):
    # virtual devices are threads sharing the host's cores: on a small
    # box the 8 per-device threads serialize, and a heavy pre-collective
    # section can overrun XLA CPU's default 40 s rendezvous termination
    # (observed on q72's exchange at 1 core: "only 2 of them arrived")
    flags += (" --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
              " --xla_cpu_collective_call_terminate_timeout_seconds=1200")
if "parallel_codegen_split_count" not in flags and _jaxlib_knows(
        "xla_cpu_parallel_codegen_split_count"):
    # one codegen unit per module so every executable the plan-cache
    # tests persist can DESERIALIZE: split codegen drops the secondary
    # units' symbols from serialized CPU executables ("Symbols not
    # found" on reload; nds_tpu/cache ensure_reloadable_codegen) and
    # the pytest process initializes jax long before any cache test
    # could pin the flag itself (~2% compile-time cost at 2 cores)
    flags += " --xla_cpu_parallel_codegen_split_count=1"
os.environ["XLA_FLAGS"] = flags
os.environ.setdefault("JAX_ENABLE_X64", "true")

# the environment's sitecustomize can override jax_platforms back to the
# remote TPU plugin after import — pin the config itself to cpu
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
