"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

This is the "multi-node without a cluster" tier (SURVEY.md §4): the
reference's analog is Spark local[*] mode (`shared/base.template:27`); ours
is XLA's host-platform device multiplexing, so every sharding/collective
path is exercised without TPU hardware.

Must run before any jax import — pytest imports conftest first.
"""

import os

# force, don't setdefault: the ambient environment may point JAX at a
# remote TPU tunnel (axon); tests must run on the local virtual CPU mesh
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "collective_call_terminate" not in flags:
    # virtual devices are threads sharing the host's cores: on a small
    # box the 8 per-device threads serialize, and a heavy pre-collective
    # section can overrun XLA CPU's default 40 s rendezvous termination
    # (observed on q72's exchange at 1 core: "only 2 of them arrived")
    flags += (" --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
              " --xla_cpu_collective_call_terminate_timeout_seconds=1200")
os.environ["XLA_FLAGS"] = flags
os.environ.setdefault("JAX_ENABLE_X64", "true")

# the environment's sitecustomize can override jax_platforms back to the
# remote TPU plugin after import — pin the config itself to cpu
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
