"""Observability layer tests (nds_tpu/obs): span nesting + attributes,
disabled-mode no-ops, the Chrome trace-event JSONL schema (golden,
gated by tools/check_trace_schema.py), the TaskFailureCollector ->
metrics bridge, timings parity between the span-fed query_timings
accessor and legacy last_timings on single-chip and virtual-mesh
distributed executors, and the end-to-end power-run contract: a
3-query NDS power run with NDS_TPU_TRACE set emits schema-valid JSONL
whose per-query span totals agree with the TimeLog CSV within 5 ms on
both executors, staged sub-program spans included."""

import json
import os
import subprocess
import sys
import threading

import pytest

from nds_tpu import obs
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.obs.trace import (
    NOOP_SPAN, Span, Tracer, export_chrome, timings_from_span,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


# --------------------------------------------------------------- tracer

class TestTracer:
    def test_span_nesting_and_attrs(self):
        tr = Tracer(enabled=True)
        with tr.span("query", query="q1") as root:
            with tr.span("sql.parse", chars=42) as p:
                pass
            with tr.span("device.execute") as ex:
                with tr.span("device.compile") as c:
                    pass
        assert [c.name for c in root.children] == ["sql.parse",
                                                   "device.execute"]
        assert ex.children == [c]
        assert root.attrs["query"] == "q1"
        assert p.attrs["chars"] == 42
        assert root.t1 is not None
        assert root.dur_ms >= ex.dur_ms >= c.dur_ms >= 0
        assert [s.name for s in root.walk()] == [
            "query", "sql.parse", "device.execute", "device.compile"]
        assert root.find("device.compile") == [c]
        # root retention for BenchReport export
        assert tr.last_roots[-1] is root

    def test_exception_closes_span_and_records_error(self):
        tr = Tracer(enabled=True)
        with pytest.raises(ValueError):
            with tr.span("query") as root:
                raise ValueError("boom")
        assert root.t1 is not None
        assert "boom" in root.attrs["error"]

    def test_begin_attach_for_async_owners(self):
        """Async executors own their span explicitly: begin() does not
        touch the thread stack; attach() makes it current for nested
        phases without ending it."""
        tr = Tracer(enabled=True)
        q = tr.begin("device.execute", parent=None)
        assert tr.current() is None
        with tr.attach(q):
            assert tr.current() is q
            with tr.span("device.materialize"):
                pass
        assert tr.current() is None
        assert q.t1 is None  # attach never ends
        run = tr.begin("device.run", parent=q, t0=q.t0)
        run.end(t=q.t0 + 0.5)
        assert abs(run.dur_ms - 500.0) < 1e-6
        q.set(timings={"execute_ms": 500.0}).end()
        assert [c.name for c in q.children] == ["device.materialize",
                                                "device.run"]
        assert tr.last_roots[-1] is q

    def test_disabled_mode_is_noop(self):
        tr = Tracer(enabled=False)
        s = tr.span("query", big_attr="x")
        assert s is NOOP_SPAN and not s
        assert s.set(a=1) is s and s.end() is s
        with s:
            pass
        assert tr.begin("device.execute") is NOOP_SPAN
        with tr.attach(s):
            assert tr.current() is None
        assert len(tr.last_roots) == 0
        assert timings_from_span(s) == {}

    def test_threads_get_independent_stacks(self):
        tr = Tracer(enabled=True)
        seen = {}

        def worker():
            seen["current"] = tr.current()
            with tr.span("query", thread="t") as s:
                seen["span"] = s

        with tr.span("query", thread="main") as root:
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["current"] is None       # no cross-thread leakage
        assert seen["span"].parent is None   # its own root
        assert root.children == []

    def test_timings_from_span_prefers_attached_dict(self):
        tr = Tracer(enabled=True)
        with tr.span("device.execute") as q:
            with tr.span("device.compile"):
                pass
        q.set(timings={"compile_ms": 7.0, "bytes_scanned": 10.0})
        assert timings_from_span(q) == {"compile_ms": 7.0,
                                        "bytes_scanned": 10.0}

    def test_timings_from_span_sums_phases(self):
        tr = Tracer(enabled=True)
        q = tr.begin("device.execute", parent=None)
        tr.begin("device.run", parent=q, t0=1.0).end(t=1.25)
        tr.begin("device.run", parent=q, t0=2.0).end(t=2.25)
        tr.begin("device.compile", parent=q, t0=0.0).end(t=0.5)
        q.end()
        t = timings_from_span(q)
        assert abs(t["execute_ms"] - 500.0) < 1e-6
        assert abs(t["compile_ms"] - 500.0) < 1e-6


# ------------------------------------------------------- chrome export

class TestChromeExport:
    def _tree(self):
        tr = Tracer(enabled=True)
        with tr.span("query", query="q96") as root:
            with tr.span("device.execute", executor="DeviceExecutor"):
                pass
        return root

    def test_export_appends_jsonl(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        root = self._tree()
        export_chrome(root, path)
        export_chrome(root, path)  # append, not truncate
        lines = [json.loads(ln) for ln in open(path)]
        assert len(lines) == 4
        assert lines[0]["name"] == "query"
        assert lines[1]["name"] == "device.execute"

    def test_event_schema_golden(self, tmp_path):
        """The documented event schema, field by field — consumers
        (Perfetto after array-wrapping, check_trace_schema.py) parse
        exactly this."""
        path = str(tmp_path / "trace.jsonl")
        export_chrome(self._tree(), path)
        ev = json.loads(open(path).readline())
        assert set(ev) == {"name", "cat", "ph", "ts", "dur", "pid",
                           "tid", "args"}
        assert ev["ph"] == "X"
        assert ev["cat"] == "query"
        assert isinstance(ev["name"], str) and ev["name"]
        assert isinstance(ev["ts"], float) and ev["ts"] >= 0
        assert isinstance(ev["dur"], float) and ev["dur"] >= 0
        assert ev["pid"] == os.getpid()
        assert isinstance(ev["tid"], int)
        assert ev["args"] == {"query": "q96"}

    def test_env_var_triggers_export_on_root_end(self, tmp_path,
                                                 monkeypatch):
        path = str(tmp_path / "auto.jsonl")
        monkeypatch.setenv("NDS_TPU_TRACE", path)
        tr = Tracer(enabled=True)
        with tr.span("query", query="auto"):
            with tr.span("sql.parse"):
                pass
        events = [json.loads(ln) for ln in open(path)]
        assert [e["name"] for e in events] == ["query", "sql.parse"]

    def test_check_trace_schema_validates(self, tmp_path):
        from tools.check_trace_schema import validate_file
        path = str(tmp_path / "trace.jsonl")
        export_chrome(self._tree(), path)
        assert validate_file(path) == []

    def test_check_trace_schema_rejects_bad_events(self, tmp_path):
        from tools.check_trace_schema import validate_event, validate_file
        assert validate_event([]) != []
        assert validate_event({"name": "x"}) != []
        good = {"name": "x", "cat": "x", "ph": "X", "ts": 0.0,
                "dur": 1.0, "pid": 1, "tid": 1, "args": {}}
        assert validate_event(good) == []
        assert validate_event({**good, "ph": "B"}) != []
        assert validate_event({**good, "dur": -1}) != []
        assert validate_event({**good, "args": 3}) != []
        bad = tmp_path / "bad.jsonl"
        bad.write_text(json.dumps(good) + "\nnot json\n")
        errs = validate_file(str(bad))
        assert len(errs) == 1 and "line 2" in errs[0]
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert validate_file(str(empty)) != []


# -------------------------------------------------------------- metrics

class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(2.5)
        reg.gauge("g").set(7)
        reg.histogram("h").observe(1.0)
        reg.histogram("h").observe(3.0)
        snap = reg.snapshot()
        assert snap["counters"]["c"] == 3.5
        assert snap["gauges"]["g"] == 7
        assert snap["histograms"]["h"] == {
            "count": 2, "sum": 4.0, "min": 1.0, "max": 3.0,
            "p50": 1.0, "p95": 3.0, "p99": 3.0}

    def test_delta(self):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("a").inc(5)
        reg.histogram("h").observe(2.0)
        before = reg.snapshot()
        reg.counter("a").inc(3)
        reg.counter("b").inc()
        reg.gauge("g").set(1)
        reg.histogram("h").observe(4.0)
        d = obs_metrics.delta(before, reg.snapshot())
        assert d["counters"] == {"a": 3, "b": 1}
        assert d["gauges"] == {"g": 1}
        # count/sum are deltas; the quantiles are the AFTER snapshot's
        # distribution state
        assert d["histograms"]["h"] == {"count": 1, "sum": 4.0,
                                        "p50": 2.0, "p95": 4.0,
                                        "p99": 4.0}
        assert obs_metrics.delta(before, before) == {}

    def test_counter_thread_safety(self):
        reg = obs_metrics.MetricsRegistry()

        def hammer():
            c = reg.counter("n")
            for _ in range(1000):
                c.inc()

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counter("n").value == 8000

    def test_task_failure_collector_bridge(self):
        """Every TaskFailureCollector.notify lands in the
        task_failures_total counter — with and without a registered
        listener."""
        from nds_tpu.utils.report import TaskFailureCollector
        before = obs_metrics.counter("task_failures_total").value
        TaskFailureCollector.notify("anomaly with nobody listening")
        col = TaskFailureCollector()
        col.register()
        try:
            TaskFailureCollector.notify("anomaly with a listener")
        finally:
            col.unregister()
        assert obs_metrics.counter(
            "task_failures_total").value == before + 2
        assert col.failures == ["anomaly with a listener"]


# ------------------------------------------------------- timings parity

SF = 0.002


@pytest.fixture(scope="module")
def tpch_raw():
    from nds_tpu.datagen import tpch
    from nds_tpu.nds_h.schema import get_schemas
    return {t: tpch.gen_table(t, SF) for t in get_schemas()}


def _nds_h_session(raw, factory=None):
    from nds_tpu.engine.session import Session
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds_h.schema import get_schemas
    schemas = get_schemas()
    sess = Session.for_nds_h(factory)
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


TIMING_KEYS = {"compile_ms", "execute_ms", "materialize_ms",
               "bytes_scanned", "scan_gbps"}


class TestTimingsParity:
    def test_single_chip_query_timings_match_last_timings(self,
                                                          tpch_raw):
        from nds_tpu.engine.device_exec import make_device_factory
        from nds_tpu.nds_h import streams
        sess = _nds_h_session(tpch_raw, make_device_factory())
        sess.sql(streams.render_query(6))
        ex = sess._executor_factory(sess.tables)
        got = obs.query_timings(ex)
        assert got == ex.last_timings
        assert TIMING_KEYS <= set(got)
        root = ex.last_query_span
        assert root.name == "device.execute"
        names = {c.name for c in root.children}
        assert {"device.compile", "device.run",
                "device.materialize"} <= names

    def test_distributed_query_timings_match_last_timings(self,
                                                          tpch_raw):
        """The multichip path reports the same timing schema as
        single-chip (round-5 advisor fix: DistributedExecutor.execute
        used to leave last_timings stale/empty)."""
        from nds_tpu.nds_h import streams
        from nds_tpu.parallel.dist_exec import make_distributed_factory
        sess = _nds_h_session(
            tpch_raw,
            make_distributed_factory(n_devices=8, shard_threshold=1000))
        sess.sql(streams.render_query(6))
        ex = sess._executor_factory(sess.tables)
        got = obs.query_timings(ex)
        assert got == ex.last_timings
        assert TIMING_KEYS <= set(got)
        assert got["execute_ms"] > 0

    def test_distributed_staged_bill_folds_into_timings(
            self, tpch_raw, monkeypatch):
        """Staged sub-programs on the multichip path must bill into
        the query's timings (the dropped-bill half of the advisor
        finding) and appear as spans."""
        from nds_tpu.engine import staging
        from nds_tpu.nds_h import streams
        from nds_tpu.parallel.dist_exec import (
            DistributedExecutor, make_distributed_factory,
        )
        monkeypatch.setattr(DistributedExecutor, "STAGE_WEIGHT", 4)
        monkeypatch.setattr(staging, "MIN_CUT_WEIGHT", 2)
        sess = _nds_h_session(
            tpch_raw,
            make_distributed_factory(n_devices=8, shard_threshold=1000))
        sess.sql(streams.render_query(3))
        ex = sess._executor_factory(sess.tables)
        tm = obs.query_timings(ex)
        assert tm.get("staged_programs", 0) >= 1
        assert tm == ex.last_timings
        assert not ex._stage_timings  # bill consumed, no leak
        assert len(ex.last_query_span.find("stage.sub")) >= 1

    def test_stage_plan_reuse_requires_pinned_plan(self, tpch_raw,
                                                   monkeypatch):
        """_stage_plans entries pin the caller's plan object; an entry
        whose pin does not match the incoming plan (recycled id() /
        rebound key) is recomputed, never served stale (round-5
        advisor finding)."""
        from nds_tpu.engine import staging
        from nds_tpu.engine.device_exec import DeviceExecutor
        from nds_tpu.nds_h import streams
        monkeypatch.setattr(DeviceExecutor, "STAGE_WEIGHT", 4)
        monkeypatch.setattr(staging, "MIN_CUT_WEIGHT", 2)
        sess = _nds_h_session(tpch_raw)
        planned_a = sess.plan(streams.render_query(3))
        planned_b = sess.plan(streams.render_query(10))
        ex = DeviceExecutor(sess.tables)
        ex.execute(planned_a, key="k")
        entry_a = ex._stage_plans["k"]
        assert entry_a[0] is planned_a
        # pin matches: the cached split is reused, not recomputed
        ex.execute(planned_a, key="k")
        assert ex._stage_plans["k"] is entry_a
        # the overflow-retry path re-dispatches the staged MAIN plan
        # under the same key: that must reuse the split (temps are
        # registered, the bill is parked) — NOT evict the compile entry
        # whose slack the retry just doubled
        main = entry_a[2]
        assert main is not planned_a
        assert ex._staged_effective(main, "k") is main
        assert ex._stage_plans["k"] is entry_a
        assert "k" in ex._compiled
        # eviction dropped the program + pinning ref, then the key was
        # recycled by a DIFFERENT plan: the stale split must not serve
        ex._compiled.pop("k")
        ex.execute(planned_b, key="k")
        assert ex._stage_plans["k"][0] is planned_b

    def test_distributed_eviction_drops_stage_state(self, tpch_raw,
                                                    monkeypatch):
        """LRU eviction of a compiled program also drops its staging
        state (including recursive sub-program keys) so recycled id()s
        can never hit a stale split."""
        from nds_tpu.engine import staging
        from nds_tpu.nds_h import streams
        from nds_tpu.parallel.dist_exec import DistributedExecutor
        monkeypatch.setattr(DistributedExecutor, "STAGE_WEIGHT", 4)
        monkeypatch.setattr(DistributedExecutor, "MAX_COMPILED", 2)
        monkeypatch.setattr(staging, "MIN_CUT_WEIGHT", 2)
        holder = {}

        def factory(tables):
            ex = holder.get("ex")
            if ex is None or ex.tables is not tables:
                ex = DistributedExecutor(tables, n_devices=8,
                                         shard_threshold=1000)
                holder["ex"] = ex
            return ex

        sess = _nds_h_session(tpch_raw, factory)
        sess.sql(streams.render_query(3))   # stages: main + sub keys
        ex = holder["ex"]
        staged_keys = set(ex._stage_plans)
        assert staged_keys
        temps = [t for e in ex._stage_plans.values() for _s, t in e[1]]
        assert temps and all(t in ex.tables for t in temps)
        sess.sql(streams.render_query(6))
        sess.sql(streams.render_query(1))
        assert len(ex._compiled) <= 2
        # q3's main AND derived sub-program staging state evicted with it
        assert not (staged_keys & set(ex._stage_plans))
        # ...including its temp tables and their caches (eviction+rerun
        # cycles must not leak staged intermediates)
        for t in temps:
            assert t not in ex.tables and t not in ex._stage_fps
            assert not any(k.startswith(t + ".") for k in ex._buffers)


# ----------------------------------------------- power-run integration

NDS_SF = 0.002
NDS_QUERIES = [96, 7, 93]


@pytest.fixture(scope="module")
def nds_power_dirs(tmp_path_factory):
    """Tiny NDS warehouse (one parquet per table) + a 3-query stream."""
    from nds_tpu.datagen import tpcds
    from nds_tpu.io import csv_io
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds import streams
    from nds_tpu.nds.schema import get_schemas
    root = tmp_path_factory.mktemp("obs_power")
    wh = root / "wh"
    wh.mkdir()
    schemas = get_schemas()
    for t, schema in schemas.items():
        table = from_arrays(t, schema, tpcds.gen_table(t, NDS_SF))
        csv_io.write_parquet(table, str(wh / f"{t}.parquet"))
    sdir = root / "streams"
    streams.generate_query_streams(str(sdir), 1,
                                   templates=NDS_QUERIES)
    return {"wh": str(wh), "stream": str(sdir / "query_0.sql"),
            "root": str(root)}


def _run_power(dirs, backend, tag, monkeypatch, tmp_path):
    from nds_tpu.engine import staging
    from nds_tpu.engine.device_exec import DeviceExecutor
    from nds_tpu.nds.power import SUITE
    from nds_tpu.parallel.dist_exec import DistributedExecutor
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    # force plan splitting so staged sub-program spans appear
    monkeypatch.setattr(DeviceExecutor, "STAGE_WEIGHT", 8)
    monkeypatch.setattr(DistributedExecutor, "STAGE_WEIGHT", 8)
    monkeypatch.setattr(staging, "MIN_CUT_WEIGHT", 2)
    trace_path = str(tmp_path / f"trace_{tag}.jsonl")
    time_log = str(tmp_path / f"time_{tag}.csv")
    summaries = str(tmp_path / f"json_{tag}")
    monkeypatch.setenv("NDS_TPU_TRACE", trace_path)
    failures = power_core.run_query_stream(
        SUITE, dirs["wh"], dirs["stream"], time_log,
        config=EngineConfig(overrides={"engine.backend": backend}),
        json_summary_folder=summaries)
    return {"failures": failures, "trace": trace_path,
            "time_log": time_log, "summaries": summaries}


def _check_power_artifacts(res):
    """The acceptance contract, shared by both backends: schema-valid
    trace, span/CSV agreement within 5 ms, staged spans present,
    engineTimings + spans + metrics in the JSON summaries."""
    from nds_tpu.utils.timelog import TimeLog
    from tools.check_trace_schema import validate_file
    assert res["failures"] == 0
    assert validate_file(res["trace"]) == []
    events = [json.loads(ln) for ln in open(res["trace"])]
    csv_ms = {q: ms for _app, q, ms in TimeLog.read(res["time_log"])}
    roots = [e for e in events if e["name"] == "query"]
    assert {e["args"]["query"] for e in roots} == {
        f"query{n}" for n in NDS_QUERIES}
    for ev in roots:
        q = ev["args"]["query"]
        span_ms = ev["dur"] / 1000.0
        assert abs(span_ms - csv_ms[q]) <= 5.0, (
            f"{q}: span {span_ms:.2f} ms vs CSV {csv_ms[q]} ms")
    # staged sub-programs traced (STAGE_WEIGHT forced low)
    assert any(e["name"] == "stage.sub" for e in events)
    assert any(e["name"] == "device.compile" for e in events)
    # JSON summaries carry the new schema fields (the resume journal,
    # <unit>_queries.json, lives in the same dir but is not a report)
    from nds_tpu.obs import analyze
    files = [f for f in os.listdir(res["summaries"])
             if analyze.is_report_basename(f)]
    assert len(files) == len(NDS_QUERIES)
    for f in files:
        with open(os.path.join(res["summaries"], f)) as fh:
            s = json.load(fh)
        assert s["queryStatus"] == ["Completed"]
        et = s["engineTimings"]
        assert et["execute_ms"] > 0 and et["bytes_scanned"] > 0
        assert et.get("staged_programs", 0) >= 1
        assert s["spans"]["name"] == "query"
        kids = [c["name"] for c in s["spans"]["children"]]
        assert "device.execute" in kids
        assert s["metrics"]["counters"]["queries_total"] == 1


class TestPowerRunTracing:
    def test_single_chip_power_run_trace(self, nds_power_dirs,
                                         monkeypatch, tmp_path):
        res = _run_power(nds_power_dirs, "tpu", "tpu", monkeypatch,
                         tmp_path)
        _check_power_artifacts(res)

    def test_distributed_power_run_trace(self, nds_power_dirs,
                                         monkeypatch, tmp_path):
        res = _run_power(nds_power_dirs, "distributed", "dist",
                         monkeypatch, tmp_path)
        _check_power_artifacts(res)


# ------------------------------------------------------------ CI gates

class TestToolGates:
    def test_check_headers_gate(self):
        """Every source file keeps its design-intent docstring (the
        repo's license-header-check analog) — run the real tool so a
        regression fails tier-1, not just CI."""
        proc = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "check_headers.py")],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout

    def test_check_trace_schema_cli(self, tmp_path):
        tr = Tracer(enabled=True)
        with tr.span("query", query="cli") as root:
            pass
        good = tmp_path / "good.jsonl"
        export_chrome(root, str(good))
        tool = os.path.join(TOOLS, "check_trace_schema.py")
        ok = subprocess.run([sys.executable, tool, str(good)],
                            capture_output=True, text=True)
        assert ok.returncode == 0, ok.stdout
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"name": "x"}\n')
        fail = subprocess.run([sys.executable, tool, str(bad)],
                              capture_output=True, text=True)
        assert fail.returncode == 1
        assert "missing key" in fail.stdout
