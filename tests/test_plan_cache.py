"""Persistent AOT plan cache (nds_tpu/cache/): fingerprints, the
sha256-stamped store, AOT (de)serialization, and the compile-once
contract end to end — including the ISSUE 7 acceptance test: a
subprocess populates the cache, the parent re-runs the same 3-query
NDS-H power stream against the same warehouse and performs ZERO
compiles with identical rows."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from nds_tpu import cache as plan_cache
from nds_tpu.cache import fingerprint as fpm
from nds_tpu.cache.store import MANIFEST_NAME, PlanCache
from nds_tpu.datagen import tpch
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h.schema import get_schemas
from nds_tpu.obs import metrics as obs_metrics

SF = 0.01


@pytest.fixture(autouse=True)
def _cache_isolation():
    """No test leaks a cache activation into the next (the resolver is
    process-global by design — one cache per engine process)."""
    plan_cache.reset()
    yield
    plan_cache.reset()


@pytest.fixture(scope="module")
def raw():
    return {t: tpch.gen_table(t, SF) for t in get_schemas()}


def _session(raw, factory=None):
    schemas = get_schemas()
    sess = Session.for_nds_h(factory)
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


def _run(sess, qn):
    from nds_tpu.nds_h import streams
    result = None
    for s in streams.statements(qn):
        r = sess.sql(s)
        if r is not None:
            result = r
    return result


def _counters(before):
    return obs_metrics.delta(before,
                             obs_metrics.snapshot()).get("counters", {})


# ------------------------------------------------------------ fingerprint

class TestFingerprint:
    def test_canonical_deterministic(self, raw):
        sess = _session(raw)
        p1 = sess.plan("select l_returnflag, sum(l_quantity) from "
                       "lineitem group by l_returnflag")
        p2 = sess.plan("select l_returnflag, sum(l_quantity) from "
                       "lineitem group by l_returnflag")
        assert fpm.canonical(p1) == fpm.canonical(p2)
        p3 = sess.plan("select l_returnflag, sum(l_tax) from "
                       "lineitem group by l_returnflag")
        assert fpm.canonical(p1) != fpm.canonical(p3)

    def test_table_digest_memoized_and_content_sensitive(self, raw):
        schemas = get_schemas()
        t1 = from_arrays("region", schemas["region"], raw["region"])
        d1 = fpm.table_digest(t1)
        assert fpm.table_digest(t1) == d1  # memo
        # same shape, different content -> different digest
        changed = dict(raw["region"])
        changed["r_regionkey"] = np.ascontiguousarray(
            np.array(changed["r_regionkey"])[::-1])
        t2 = from_arrays("region", schemas["region"], changed)
        assert fpm.table_digest(t2) != d1

    def test_fingerprint_components(self, raw):
        sess = _session(raw)
        p = sess.plan("select count(*) from region")
        base = fpm.fingerprint(p, sess.tables, kind="DeviceExecutor",
                               parts={"slack": 1.0})
        assert base == fpm.fingerprint(p, sess.tables,
                                       kind="DeviceExecutor",
                                       parts={"slack": 1.0})
        assert base != fpm.fingerprint(p, sess.tables,
                                       kind="DeviceExecutor",
                                       parts={"slack": 2.0})
        assert base != fpm.fingerprint(p, sess.tables,
                                       kind="DistributedExecutor",
                                       parts={"slack": 1.0})
        # extra roots (the partial-agg merge plan) shape the key
        p2 = sess.plan("select count(*) from nation")
        assert base != fpm.fingerprint(p, sess.tables,
                                       kind="DeviceExecutor",
                                       parts={"slack": 1.0},
                                       extra_roots=[p2.root])

    def test_fingerprint_tracks_table_content(self, raw):
        sess = _session(raw)
        p = sess.plan("select count(*) from region where r_regionkey=1")
        base = fpm.fingerprint(p, sess.tables, kind="x", parts={})
        schemas = get_schemas()
        changed = dict(raw["region"])
        changed["r_regionkey"] = np.ascontiguousarray(
            np.array(changed["r_regionkey"]) + 1)
        tables2 = dict(sess.tables)
        tables2["region"] = from_arrays("region", schemas["region"],
                                        changed)
        assert fpm.fingerprint(p, tables2, kind="x", parts={}) != base

    def test_code_epoch_stable(self):
        assert fpm.code_epoch() == fpm.code_epoch()
        assert len(fpm.code_epoch()) == 64


# ------------------------------------------------------------------ store

class TestStore:
    FP = "ab" + "0" * 62

    def test_roundtrip(self, tmp_path):
        store = PlanCache(str(tmp_path / "c"))
        payload = {"exec": b"\x00" * 256, "extra": {"dicts": [1, 2]}}
        assert store.put(self.FP, payload, meta={"kind": "T"})
        assert store.get(self.FP, expect_kind="T") == payload
        # kind mismatch degrades to a miss, not an error
        assert store.get(self.FP, expect_kind="Other") is None

    def test_missing_is_quiet_miss(self, tmp_path):
        store = PlanCache(str(tmp_path / "c"))
        before = obs_metrics.snapshot()
        assert store.get(self.FP) is None
        d = _counters(before)
        assert d.get("compile_cache_misses_total") == 1
        assert not d.get("compile_cache_errors_total")

    def test_corruption_quarantines_and_warns(self, tmp_path, capsys):
        store = PlanCache(str(tmp_path / "c"))
        store.put(self.FP, {"exec": b"\x01" * 512})
        payload_path = store.payload_path(self.FP)
        with open(payload_path, "r+b") as f:
            f.seek(100)
            f.write(b"\xff")
        before = obs_metrics.snapshot()
        assert store.get(self.FP) is None
        d = _counters(before)
        assert d.get("compile_cache_errors_total") == 1
        assert "corrupt entry" in capsys.readouterr().out
        # quarantined: inventory is empty, nothing re-diagnoses it
        assert store.entries() == []
        assert not os.path.exists(store.entry_dir(self.FP))
        # prune --corrupt clears the husk
        removed = store.prune(corrupt=True)
        assert any(".corrupt-" in fp for fp in removed)

    def test_version_skew_degrades(self, tmp_path):
        store = PlanCache(str(tmp_path / "c"))
        store.put(self.FP, {"exec": b"\x02" * 64})
        mpath = os.path.join(store.entry_dir(self.FP), MANIFEST_NAME)
        with open(mpath) as f:
            m = json.load(f)
        m["store_version"] = 99
        with open(mpath, "w") as f:
            json.dump(m, f)
        before = obs_metrics.snapshot()
        assert store.get(self.FP) is None
        assert _counters(before).get("compile_cache_errors_total") == 1

    def test_readonly_never_writes(self, tmp_path):
        root = str(tmp_path / "ro")
        PlanCache(root).put(self.FP, {"exec": b"\x03"})
        store = PlanCache(root, readonly=True)
        assert not store.put("cd" + "0" * 62, {"exec": b"\x04"})
        assert [m["fingerprint"] for m in store.entries()] == [self.FP]
        # readonly quarantine is a no-op: the entry stays
        store._quarantine(self.FP)
        assert store.get(self.FP) is not None

    def test_prune_by_age_and_jax(self, tmp_path):
        store = PlanCache(str(tmp_path / "c"))
        store.put(self.FP, {"exec": b"\x05"}, meta={"jax": "0.0.1"})
        other = "ef" + "0" * 62
        store.put(other, {"exec": b"\x06"}, meta={"jax": "9.9.9"})
        assert store.prune(keep_days=1) == []
        removed = store.prune(jax_version="9.9.9")
        assert removed == [self.FP]
        assert [m["fingerprint"] for m in store.entries()] == [other]


# ----------------------------------------------------------- aot runtime

class TestAot:
    def test_cached_compile_roundtrip(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from nds_tpu.cache import aot
        store = PlanCache(str(tmp_path / "c"))
        fp = "12" + "0" * 62
        x = np.arange(64, dtype=np.float32)
        calls = []

        def build():
            calls.append(1)
            return jax.jit(lambda a: jnp.cumsum(a) * 2)

        c1, extra1, hit1 = aot.cached_compile(
            store, fp, "T", build, (x,),
            extra_fn=lambda: {"dicts": ["d"]})
        assert not hit1 and calls == [1]
        timings = {}
        c2, extra2, hit2 = aot.cached_compile(
            store, fp, "T", build, (x,), timings=timings)
        assert hit2 and calls == [1]          # build() never re-ran
        assert extra2 == {"dicts": ["d"]}
        assert timings["cache_load_ms"] > 0
        assert np.array_equal(np.asarray(c1(x)), np.asarray(c2(x)))

    def test_incompatible_signature_is_miss(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from nds_tpu.cache import aot
        store = PlanCache(str(tmp_path / "c"))
        fp = "34" + "0" * 62
        x = np.arange(64, dtype=np.float32)
        aot.cached_compile(store, fp, "T",
                           lambda: jax.jit(jnp.cumsum), (x,))
        y = np.arange(128, dtype=np.float64)
        hit = aot.load_cached(store, fp, "T", args=(y,))
        assert hit is None  # shape/dtype drift degrades to a miss

    def test_platform_parts_key_the_backend(self):
        from nds_tpu.cache import aot
        parts = aot.platform_parts()
        assert parts["platform"] == "cpu"
        assert "jax" in parts and "jaxlib" in parts


# ----------------------------------------- executor integration (device)

class TestDeviceWarm:
    def test_second_executor_serves_warm(self, raw, tmp_path):
        from nds_tpu.engine.device_exec import make_device_factory
        plan_cache.configure(str(tmp_path / "pc"))
        before = obs_metrics.snapshot()
        a = _run(_session(raw, make_device_factory()), 1)
        cold = _counters(before)
        assert cold.get("compiles_total", 0) >= 1
        assert cold.get("compile_cache_bytes_written_total", 0) > 0
        # a NEW executor (fresh in-memory caches) in the same process:
        # every program deserializes from disk, zero compiles
        before = obs_metrics.snapshot()
        b = _run(_session(raw, make_device_factory()), 1)
        warm = _counters(before)
        assert not warm.get("compiles_total")
        assert not warm.get("recompiles_total")
        assert warm.get("compile_cache_hits_total", 0) >= 1
        assert a.to_pandas().equals(b.to_pandas())

    def test_chunked_executor_serves_warm(self, raw, tmp_path):
        """The out-of-core engine's sub-programs (phase-A chunk scans +
        phase-B partials) consult the same store: a fresh chunked
        executor against a warm cache compiles nothing."""
        from nds_tpu.engine.chunked_exec import make_chunked_factory
        plan_cache.configure(str(tmp_path / "pc"))

        def factory():
            # tiny stream threshold: lineitem really streams in chunks
            return make_chunked_factory(stream_bytes=1 << 16,
                                        chunk_rows=4096)
        before = obs_metrics.snapshot()
        a = _run(_session(raw, factory()), 6)
        cold = _counters(before)
        assert cold.get("compiles_total", 0) >= 1
        before = obs_metrics.snapshot()
        b = _run(_session(raw, factory()), 6)
        warm = _counters(before)
        assert not warm.get("compiles_total"), warm
        assert warm.get("compile_cache_hits_total", 0) >= 1
        assert a.to_pandas().equals(b.to_pandas())

    def test_distributed_executor_serves_warm(self, raw, tmp_path):
        """Sharded programs round-trip too (single-process worlds; a
        multi-controller run falls back to jax's own XLA cache): a
        fresh executor on the same 8-device virtual mesh serves every
        program — including slack-grown recompiles — from disk."""
        from nds_tpu.parallel.dist_exec import make_distributed_factory
        plan_cache.configure(str(tmp_path / "pc"))
        before = obs_metrics.snapshot()
        a = _run(_session(raw, make_distributed_factory(n_devices=8)),
                 6)
        cold = _counters(before)
        assert (cold.get("compiles_total", 0)
                + cold.get("recompiles_total", 0)) >= 1
        before = obs_metrics.snapshot()
        b = _run(_session(raw, make_distributed_factory(n_devices=8)),
                 6)
        warm = _counters(before)
        assert not warm.get("compiles_total"), warm
        assert not warm.get("recompiles_total"), warm
        assert warm.get("compile_cache_hits_total", 0) >= 1
        assert a.to_pandas().equals(b.to_pandas())

    def test_cache_off_is_null_change(self, raw):
        from nds_tpu.engine.device_exec import make_device_factory
        plan_cache.configure(None)  # explicit off
        before = obs_metrics.snapshot()
        _run(_session(raw, make_device_factory()), 6)
        d = _counters(before)
        assert d.get("compiles_total", 0) >= 1
        assert not d.get("compile_cache_misses_total")
        assert not d.get("compile_cache_hits_total")


# -------------------------------------------- cross-process warm start

@pytest.fixture(scope="module")
def nds_h_warehouse(tmp_path_factory):
    """Tiny NDS-H warehouse + power stream shared by the warm-start
    test: the subprocess and the parent must load IDENTICAL table
    content or the fingerprints (content stamps) would not match."""
    from nds_tpu.nds_h import gen_data, streams, transcode
    root = tmp_path_factory.mktemp("nds_h_wh")
    raw_dir = str(root / "raw")
    wh = str(root / "wh")
    gen_data.generate_data_local(SF, 2, raw_dir, workers=2)
    transcode.transcode(raw_dir, wh, str(root / "load_report.txt"))
    sdir = str(root / "streams")
    streams.generate_query_streams(sdir, 1)
    return {"wh": wh, "stream": os.path.join(sdir, "stream_0.sql"),
            "root": str(root)}


WARM_SUBSET = ["query1", "query6", "query12"]

_CHILD = """
import sys
from nds_tpu.nds_h.power import SUITE
from nds_tpu.utils import power_core
from nds_tpu.utils.config import EngineConfig

wh, stream, tlog, jsons, out = sys.argv[1:6]
cfg = EngineConfig(overrides={
    "engine.backend": "tpu",
    "engine.placement.force": "device",
})
failures = power_core.run_query_stream(
    SUITE, wh, stream, tlog, config=cfg,
    json_summary_folder=jsons, output_prefix=out,
    query_subset="@SUBSET@".split(","))
sys.exit(failures)
"""


class TestCrossProcessWarmStart:
    def test_warm_start_zero_compiles(self, nds_h_warehouse, tmp_path):
        """ISSUE 7 acceptance: subprocess populates the cache; the
        parent re-runs the same 3-query power stream and performs 0
        compiles with identical rows."""
        from nds_tpu.io.result_io import read_result
        from nds_tpu.nds_h.power import SUITE
        from nds_tpu.utils import power_core
        from nds_tpu.utils.config import EngineConfig

        cache_dir = str(tmp_path / "pc")
        child_out = str(tmp_path / "child_rows")
        env = dict(os.environ)
        env["NDS_TPU_PLAN_CACHE"] = cache_dir  # the env activation path
        env.setdefault("JAX_PLATFORMS", "cpu")
        script = _CHILD.replace("@SUBSET@", ",".join(WARM_SUBSET))
        proc = subprocess.run(
            [sys.executable, "-c", script, nds_h_warehouse["wh"],
             nds_h_warehouse["stream"], str(tmp_path / "child.csv"),
             str(tmp_path / "child_json"), child_out],
            env=env, capture_output=True, text=True, timeout=900)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        store = PlanCache(cache_dir, readonly=True)
        assert store.entries(), "subprocess persisted nothing"
        assert store.verify() == []

        # parent rerun: config activation path, same warehouse
        jsons = str(tmp_path / "parent_json")
        parent_out = str(tmp_path / "parent_rows")
        cfg = EngineConfig(overrides={
            "engine.backend": "tpu",
            "engine.placement.force": "device",
            "cache.dir": cache_dir,
        })
        before = obs_metrics.snapshot()
        failures = power_core.run_query_stream(
            SUITE, nds_h_warehouse["wh"], nds_h_warehouse["stream"],
            str(tmp_path / "parent.csv"), config=cfg,
            json_summary_folder=jsons, output_prefix=parent_out,
            query_subset=WARM_SUBSET)
        d = _counters(before)
        assert failures == 0
        # THE acceptance numbers: zero compiles, hits for every query
        assert not d.get("compiles_total"), d
        assert not d.get("recompiles_total"), d
        assert d.get("compile_cache_hits_total", 0) >= len(WARM_SUBSET)
        assert not d.get("compile_cache_errors_total"), d

        summaries = {}
        for f in os.listdir(jsons):
            with open(os.path.join(jsons, f)) as fh:
                s = json.load(fh)
            # the run dir also holds the resume journal
            # (<unit>_queries.json) — only BenchReports count here
            if isinstance(s, dict) and "query" in s:
                summaries[s["query"]] = s
        for q in WARM_SUBSET:
            s = summaries[q]
            assert s["queryStatus"] == ["Completed"], s["queryStatus"]
            # BenchReport cache block: all hits, no misses
            assert s["cache"]["hits"] >= 1, s.get("cache")
            assert s["cache"]["misses"] == 0, s.get("cache")
            assert s["cache"]["load_ms"] > 0
            # compile_ms stays 0 on the hit path; the deserialize cost
            # is billed separately
            assert s["engineTimings"].get("compile_ms", 0) == 0, \
                s["engineTimings"]
            assert s["engineTimings"]["cache_load_ms"] > 0
        # identical rows, child vs parent
        for q in WARM_SUBSET:
            a = read_result(os.path.join(child_out, q))
            b = read_result(os.path.join(parent_out, q))
            assert a.equals(b), f"{q} rows diverged across processes"


# ------------------------------------------------------- ndscache CLI

class TestNdsCacheCli:
    def _tool(self):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import ndscache
        return ndscache

    def test_ls_verify_prune(self, tmp_path, capsys):
        ndscache = self._tool()
        root = str(tmp_path / "c")
        store = PlanCache(root)
        fp = "ab" + "1" * 62
        store.put(fp, {"exec": b"\x00" * 128}, meta={"kind": "T"})
        assert ndscache.main(["ls", "--dir", root]) == 0
        out = capsys.readouterr().out
        assert fp[:16] in out and "1 entry" in out
        assert ndscache.main(["verify", "--dir", root]) == 0
        # corrupt it -> verify exits 1, prune --corrupt removes it
        p = store.payload_path(fp)
        with open(p, "r+b") as f:
            f.write(b"\xee")
        assert ndscache.main(["verify", "--dir", root]) == 1
        assert ndscache.main(["prune", "--dir", root, "--corrupt"]) == 0
        assert ndscache.main(["verify", "--dir", root]) == 0
        assert "0 corrupt of 0" in capsys.readouterr().out

    def test_warm_subset_then_all_hits(self, tmp_path, capsys):
        """`ndscache warm` compiles a statement subset into a cold
        cache on bare CPU; warming again serves every program from the
        cache (the acceptance sweep runs all 125 — tier-1 proves the
        mechanism on two)."""
        ndscache = self._tool()
        root = str(tmp_path / "c")
        before = obs_metrics.snapshot()
        rc = ndscache.main(["warm", "--dir", root, "--suite", "nds_h",
                            "--sf", "0.002", "--queries", "q1", "q6"])
        assert rc == 0
        cold = _counters(before)
        assert cold.get("compiles_total", 0) >= 2
        assert "warmed 2 statement(s) (0 failed)" in \
            capsys.readouterr().out
        store = PlanCache(root, readonly=True)
        assert store.entries() and store.verify() == []
        plan_cache.reset()
        before = obs_metrics.snapshot()
        assert ndscache.main(["warm", "--dir", root, "--suite",
                              "nds_h", "--sf", "0.002", "--queries",
                              "q1", "q6"]) == 0
        warm = _counters(before)
        assert not warm.get("compiles_total")
        assert warm.get("compile_cache_hits_total", 0) >= 2


# ------------------------------------------------- summary schema gate

class TestSummarySchema:
    def _validate(self, cache_block):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import check_trace_schema as cts
        obj = {"query": "q", "queryStatus": ["Completed"],
               "queryTimes": [1], "startTime": 1, "env": {},
               "cache": cache_block}
        return cts.validate_summary(obj)

    def test_cache_block_valid(self):
        assert self._validate({"hits": 2, "misses": 0}) == []
        assert self._validate({"hits": 0, "misses": 3, "errors": 1,
                               "bytes_read": 10, "bytes_written": 20,
                               "load_ms": 1.5}) == []

    def test_cache_block_invalid(self):
        assert self._validate({"hits": 2})            # misses missing
        assert self._validate({"hits": -1, "misses": 0})
        assert self._validate({"hits": 1, "misses": 0,
                               "load_ms": "fast"})
        assert self._validate({"hits": 1, "misses": 0,
                               "bytes_read": -5})


# ------------------------------------- parameterized-fingerprint sharing

class TestParameterizedSharing:
    """ISSUE 12: same-template literal variants must land on ONE cache
    entry and pay zero compiles after the first (sql/params.py)."""

    def _variant(self, seed: int) -> str:
        import random

        from nds_tpu.nds_h import streams as hs
        return hs.render_query(5, hs.random_params(
            5, random.Random(seed), 0))

    def test_two_literal_variants_one_entry_zero_miss(self, raw,
                                                      tmp_path):
        from nds_tpu.engine.device_exec import make_device_factory
        plan_cache.configure(str(tmp_path / "pc"))
        dev = _session(raw, make_device_factory())
        dev.parameterize = True
        oracle = _session(raw)
        a, b = self._variant(31), self._variant(32)
        assert a != b, "variants must differ in literals"

        before = obs_metrics.snapshot()
        ra = dev.sql(a)
        cold = _counters(before)
        assert cold.get("compiles_total", 0) >= 1
        store = PlanCache(str(tmp_path / "pc"), readonly=True)
        entries_cold = len(store.entries())

        before = obs_metrics.snapshot()
        rb = dev.sql(b)
        warm = _counters(before)
        # the literal variant shares the in-process compiled program:
        # no compile, no cache consult, no new entry
        assert not warm.get("compiles_total")
        assert not warm.get("compile_cache_misses_total")
        assert len(store.entries()) == entries_cold

        # parity: each variant's rows equal the CPU oracle's for the
        # SAME literals
        from test_device_engine import assert_frames_close
        assert_frames_close(ra.to_pandas(), oracle.sql(a).to_pandas(),
                            5)
        assert_frames_close(rb.to_pandas(), oracle.sql(b).to_pandas(),
                            5)

    def test_variant_hits_across_processes_via_store(self, raw,
                                                     tmp_path):
        """Variant B in a FRESH executor (new in-process caches) must
        be served by the store entry variant A persisted — the
        cross-process sharing the fingerprint identity buys."""
        from nds_tpu.engine.device_exec import make_device_factory
        plan_cache.configure(str(tmp_path / "pc"))
        dev_a = _session(raw, make_device_factory())
        dev_a.parameterize = True
        dev_a.sql(self._variant(41))

        dev_b = _session(raw, make_device_factory())
        dev_b.parameterize = True
        before = obs_metrics.snapshot()
        dev_b.sql(self._variant(42))
        warm = _counters(before)
        assert not warm.get("compiles_total")
        assert warm.get("compile_cache_hits_total", 0) >= 1
