"""Serving layer (nds_tpu/serve/) + parameterized plans (sql/params.py):

- fingerprint identity across literal variants for EVERY NDS + NDS-H
  template (ISSUE 12 satellite; q66 is the documented exception — its
  variant literal lands in a string-constant output column whose
  dictionary bakes into the program);
- hoisted-literal execution parity against the inlined-literal plan on
  the CPU oracle and the device engine;
- QueryServer admission/brownout semantics (queue depth, deadline,
  stop-drain, error answers), template batching, per-tenant metrics on
  the OpenMetrics emitter, the TCP JSON-lines front, and the
  per-request summary schema;
- ndsreport: per-tenant quantiles from serve run dirs, and the
  stale-metric refusal (bench exit codes + diff gate).
"""

import json
import os
import random
import sys
import time

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

from nds_tpu.cache import fingerprint as fpm
from nds_tpu.engine.session import Session
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.sql import ir
from nds_tpu.sql import params as sqlparams

# templates whose generator-varied literal provably cannot hoist (the
# value becomes a string-constant OUTPUT column -> its dictionary is a
# trace constant); everything else must share fingerprints
FP_EXCEPTIONS_NDS = {66}


def _apply_view_actions(sess, planned):
    act, name, node = planned
    if act == "create_view":
        sess.views[name] = node
    elif act == "drop_view":
        sess.views.pop(name, None)


def _fps_for(sess, stmts):
    out = []
    for stmt in stmts:
        planned = sess.plan(stmt)
        if isinstance(planned, tuple):
            _apply_view_actions(sess, planned)
            continue
        # a literal-free statement (q76 renders none) hoists nothing —
        # identity across variants is then trivially required
        out.append(fpm.fingerprint(planned, {}, kind="t", parts={}))
    return out


class TestFingerprintIdentity:
    def test_nds_h_all_templates_share(self):
        from nds_tpu.nds_h import streams as hs
        sess = Session.for_nds_h(parameterize=True)
        for qn in range(1, 23):
            per_seed = []
            for seed in (1, 2):
                sql = hs.render_query(
                    qn, hs.random_params(qn, random.Random(seed), 0))
                per_seed.append(_fps_for(sess, hs.statements(qn, sql)))
            assert per_seed[0] == per_seed[1], \
                f"NDS-H q{qn}: literal variants changed the fingerprint"

    def test_nds_all_templates_share(self):
        from nds_tpu.nds import streams as ds
        sess = Session.for_nds(parameterize=True)
        differing = []
        for qn in ds.available_templates():
            per_seed = []
            for seed in (1, 2):
                sql = ds.render_query(
                    qn, ds.random_params(qn, random.Random(seed), 0))
                stmts = [s.strip() for s in sql.split(";")
                         if s.strip()]
                per_seed.append(_fps_for(sess, stmts))
            if per_seed[0] != per_seed[1]:
                differing.append(qn)
        assert set(differing) <= FP_EXCEPTIONS_NDS, \
            f"unexpected fingerprint drift: {sorted(differing)}"

    def test_param_values_do_not_reach_canonical(self):
        sess = Session.for_nds_h(parameterize=True)
        p = sess.plan("select count(*) from lineitem "
                      "where l_quantity < 24")
        assert sqlparams.has_params(p)
        assert "24" not in fpm.canonical(p)
        assert any(isinstance(x, ir.ParamRef)
                   for e in _all_plan_exprs(p) for x in ir.walk(e))


def _all_plan_exprs(planned):
    from nds_tpu.sql import plan as P
    for root in [planned.root, *planned.scalar_subplans]:
        for node in P.walk_plan(root):
            for e in P.all_exprs(node):
                if e is not None:
                    yield e


# ------------------------------------------------------------- parity

@pytest.fixture(scope="module")
def h_tables():
    from nds_tpu.datagen import tpch
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds_h.schema import get_schemas
    schemas = get_schemas()
    return {t: from_arrays(t, schemas[t], tpch.gen_table(t, 0.01))
            for t in schemas}


def _h_session(h_tables, factory=None, param=False):
    s = Session.for_nds_h(factory, parameterize=param)
    for t in h_tables.values():
        s.register_table(t)
    return s


class TestParity:
    # dictionary predicates (LIKE/cmp/inlist incl. the q22 substring
    # chain), numeric/date/decimal scalars, numeric in-lists
    TEMPLATES = (1, 3, 6, 12, 13, 16, 19, 22)

    def test_inline_roundtrip_equals_plain_cpu(self, h_tables):
        """parameterize -> inline must execute EXACTLY like the plain
        plan on the oracle (the executors' inline() path)."""
        from test_device_engine import assert_frames_close

        from nds_tpu.nds_h import streams as hs
        plain = _h_session(h_tables)
        param = _h_session(h_tables, param=True)
        for qn in self.TEMPLATES:
            sql = hs.render_query(
                qn, hs.random_params(qn, random.Random(5), 0))
            exp = plain.sql(sql)
            got = param.sql(sql)
            assert_frames_close(got.to_pandas(), exp.to_pandas(), qn)

    def test_device_params_equal_plain_cpu(self, h_tables):
        """The device engine's NATIVE parameter path (runtime scalar +
        dictionary-table inputs) returns the oracle's rows."""
        from test_device_engine import assert_frames_close

        from nds_tpu.engine.device_exec import make_device_factory
        from nds_tpu.nds_h import streams as hs
        plain = _h_session(h_tables)
        dev = _h_session(h_tables, make_device_factory(), param=True)
        for qn in self.TEMPLATES:
            sql = hs.render_query(
                qn, hs.random_params(qn, random.Random(6), 0))
            exp = plain.sql(sql)
            got = dev.sql(sql)
            assert_frames_close(got.to_pandas(), exp.to_pandas(), qn)

    def test_device_shares_program_across_variants(self, h_tables):
        from nds_tpu.engine.device_exec import make_device_factory
        from nds_tpu.nds_h import streams as hs
        dev = _h_session(h_tables, make_device_factory(), param=True)
        dev.sql(hs.render_query(
            6, hs.random_params(6, random.Random(1), 0)))
        before = obs_metrics.snapshot()
        dev.sql(hs.render_query(
            6, hs.random_params(6, random.Random(2), 0)))
        delta = obs_metrics.delta(
            before, obs_metrics.snapshot()).get("counters", {})
        assert not delta.get("compiles_total"), \
            "literal variant recompiled instead of rebinding params"

    def test_compiled_entry_bound(self, h_tables, monkeypatch):
        """A serving workload cycles unbounded plan objects through the
        executor: the compile cache must evict past MAX_COMPILED
        instead of pinning plans + programs forever."""
        from nds_tpu.engine.device_exec import (
            DeviceExecutor, make_device_factory,
        )
        monkeypatch.setattr(DeviceExecutor, "MAX_COMPILED", 3)
        dev = _h_session(h_tables, make_device_factory())
        for i in range(6):
            dev.sql(f"select count(*) from region where "
                    f"r_regionkey < {i}")
        ex = dev._executor_factory(dev.tables)
        assert len(ex._compiled) <= 3

    def test_dict_binder_matches_trace(self, h_tables):
        """derive_dictionary replays substr/upper chains exactly like
        the trace's np.unique rewrites."""
        import numpy as np
        d = sqlparams.derive_dictionary(
            (("substr", 1, 2),), {"customer": h_tables["customer"]},
            "customer", "c_phone")
        base = np.asarray(
            h_tables["customer"].columns["c_phone"].dictionary)
        exp = np.unique(np.array([str(s)[0:2] for s in base]))
        assert list(d.astype(str)) == list(exp)


# ------------------------------------------------------------- server

@pytest.fixture()
def server(h_tables, tmp_path):
    from nds_tpu.serve import QueryServer
    from nds_tpu.utils.config import EngineConfig
    cfg = EngineConfig(overrides={
        "engine.backend": "cpu",
        "serve.max_queue": "4",
        "serve.summary_dir": str(tmp_path / "serve_json"),
    })
    srv = QueryServer(cfg)
    for t in h_tables.values():
        srv.register_table(t, "nds_h")
    srv.start()
    yield srv
    srv.stop()


def _submit_q6(srv, tenant="t0", qname="q6"):
    from nds_tpu.nds_h import streams as hs
    return srv.submit(tenant, "nds_h", hs.render_query(6), qname)


class TestQueryServer:
    def test_ok_response_with_digest_and_summary(self, server,
                                                 tmp_path):
        import check_trace_schema as cts
        resp = _submit_q6(server).result(timeout=120)
        assert resp.status == "ok"
        assert resp.rows >= 1 and resp.digest
        sdir = str(tmp_path / "serve_json")
        files = os.listdir(sdir)
        assert files
        for f in files:
            assert cts.validate_summary_file(
                os.path.join(sdir, f)) == []
            doc = json.load(open(os.path.join(sdir, f)))
            assert doc["tenant"] == "t0"

    def test_unknown_suite_and_bad_sql_answer_error(self, server):
        r = server.submit("t0", "nope", "select 1").result(timeout=60)
        assert r.status == "error" and "suite" in r.error
        r = server.submit("t0", "nds_h",
                          "select frobnicate from lineitem"
                          ).result(timeout=120)
        assert r.status == "error"
        # the server keeps serving after an error answer
        assert _submit_q6(server).result(timeout=120).status == "ok"

    def test_queue_depth_brownout_and_recovery(self, server):
        import ndsload
        docs = ndsload.build_requests(24, 3, tenants=2,
                                      nds_h_templates=(1, 5, 6),
                                      nds_templates=())
        responses = ndsload.burst_inproc(server, docs)
        summary = ndsload.summarize(responses)
        assert summary["status"].get("shed", 0) > 0, summary
        assert summary["status"].get("error", 0) == 0, summary
        assert obs_metrics.snapshot()["counters"].get(
            "server_shed_total", 0) > 0
        assert summary.get("shed_reasons", {}).get("queue-depth") \
            == summary["status"]["shed"]
        # brownout, not collapse
        assert _submit_q6(server).result(timeout=120).status == "ok"

    def test_deadline_shed(self, h_tables, tmp_path):
        from nds_tpu.serve import QueryServer
        from nds_tpu.utils.config import EngineConfig
        srv = QueryServer(EngineConfig(overrides={
            "engine.backend": "cpu",
            "serve.deadline_ms": "1",
        }))
        for t in h_tables.values():
            srv.register_table(t, "nds_h")
        # enqueue BEFORE starting the engine thread: the queued request
        # ages past the deadline and must shed at dequeue
        fut = _submit_q6(srv, qname="late")
        time.sleep(0.05)
        srv.start()
        try:
            r = fut.result(timeout=60)
            assert r.status == "shed" and "deadline" in r.shed_reason
        finally:
            srv.stop()

    def test_stop_sheds_queued(self, h_tables):
        from nds_tpu.serve import QueryServer
        from nds_tpu.utils.config import EngineConfig
        srv = QueryServer(EngineConfig(overrides={
            "engine.backend": "cpu"}))
        for t in h_tables.values():
            srv.register_table(t, "nds_h")
        fut = _submit_q6(srv)  # engine thread never started
        srv.stop()
        assert fut.result(timeout=10).status == "shed"
        # post-stop submits answer immediately instead of stranding
        r = _submit_q6(srv).result(timeout=10)
        assert r.status == "shed" and "stopping" in r.shed_reason
        # and a RESTARTED server serves again (no zombie-shed flag)
        srv.start()
        try:
            assert _submit_q6(srv).result(timeout=120).status == "ok"
        finally:
            srv.stop()

    def test_tenant_labels_in_openmetrics(self, server):
        from nds_tpu.obs.snapshot import (
            to_openmetrics, validate_openmetrics,
        )
        _submit_q6(server, tenant="alice").result(timeout=120)
        _submit_q6(server, tenant="bob").result(timeout=120)
        om = to_openmetrics(obs_metrics.snapshot())
        assert validate_openmetrics(om) == []
        assert 'server_requests_total{tenant="alice"}' in om
        assert 'server_requests_total{tenant="bob"}' in om
        assert '{tenant="alice",quantile="0.99"}' in om

    def test_tcp_front_roundtrip(self, server):
        import asyncio

        from nds_tpu.nds_h import streams as hs
        from nds_tpu.serve.net import request_many, start_tcp

        async def _go():
            tcp = await start_tcp(server, "127.0.0.1", 0)
            port = tcp.sockets[0].getsockname()[1]
            docs = [{"tenant": "net", "suite": "nds_h",
                     "qname": f"net{i}", "sql": hs.render_query(6)}
                    for i in range(4)]
            docs.append({"tenant": "net", "bogus": True})  # no sql
            out = await request_many("127.0.0.1", port, docs, 2)
            tcp.close()
            await tcp.wait_closed()
            return out

        out = asyncio.run(_go())
        assert [r["status"] for r in out[:4]] == ["ok"] * 4
        assert out[4]["status"] == "error"


# --------------------------------------------------- metrics + analyze

class TestLabeledMetrics:
    def test_labeled_and_split(self):
        name = obs_metrics.labeled("x_total", tenant="a b",
                                   suite="nds")
        assert name == 'x_total{suite="nds",tenant="a b"}'
        base, labels = obs_metrics.split_labels(name)
        assert base == "x_total"
        assert labels == '{suite="nds",tenant="a b"}'
        assert obs_metrics.split_labels("plain") == ("plain", "")

    def test_label_values_escaped_stay_distinct(self):
        a = obs_metrics.labeled("x", t='acme')
        b = obs_metrics.labeled("x", t='acme"')
        c = obs_metrics.labeled("x", t="a\\b")
        d = obs_metrics.labeled("x", t="ab")
        assert len({a, b, c, d}) == 4
        assert b == 'x{t="acme\\""}'
        # and the OpenMetrics renderer/validator accept escaped values
        from nds_tpu.obs.snapshot import (
            to_openmetrics, validate_openmetrics,
        )
        snap = {"counters": {obs_metrics.labeled(
            "esc_total", t='q"v\\x'): 1}}
        assert validate_openmetrics(to_openmetrics(snap)) == []


class TestAnalyzeTenants:
    def _summary(self, qname, tenant, wall_ms, **extra):
        return {"query": qname, "queryStatus": ["Completed"],
                "queryTimes": [wall_ms], "startTime": 1,
                "env": {}, "tenant": tenant, **extra}

    def _write(self, d, docs):
        os.makedirs(d, exist_ok=True)
        for i, doc in enumerate(docs):
            with open(os.path.join(d, f"serve-q{i}-{i}.json"),
                      "w") as f:
                json.dump(doc, f)

    def test_tenant_quantiles(self, tmp_path):
        from nds_tpu.obs import analyze
        d = str(tmp_path / "run")
        self._write(d, [self._summary(f"q{i}", "t0", 10 * (i + 1))
                        for i in range(10)]
                    + [self._summary("qx", "t1", 5)])
        a = analyze.analyze_run(d, with_trace=False)
        assert a["tenants"]["t0"]["requests"] == 10
        assert a["tenants"]["t0"]["p50_ms"] == 50.0
        assert a["tenants"]["t0"]["p99_ms"] == 100.0
        assert a["tenants"]["t1"]["requests"] == 1

    def test_stale_marker_fails_diff(self, tmp_path):
        from nds_tpu.obs import analyze
        clean = str(tmp_path / "clean")
        stale = str(tmp_path / "stale")
        docs = [self._summary(f"q{i}", "t0", 10.0) for i in range(3)]
        self._write(clean, docs)
        self._write(stale, [dict(doc, stale_device_times=True)
                            for doc in docs])
        a_clean = analyze.analyze_run(clean, with_trace=False)
        a_stale = analyze.analyze_run(stale, with_trace=False)
        assert "stale_device_times" not in a_clean
        assert len(a_stale["stale_device_times"]) == 3
        d = analyze.diff_runs(a_clean, a_stale)
        assert d["passed"] is False
        assert "cur" in d["stale_device_times"]
        # identical CLEAN dirs still pass
        assert analyze.diff_runs(a_clean, a_clean)["passed"] is True


# ----------------------------------------------------- bench stale exit

class TestBenchStaleExit:
    def test_stale_bank_emits_but_fails(self, tmp_path, monkeypatch,
                                        capsys):
        import bench
        monkeypatch.setattr(bench, "DATA_ROOT", str(tmp_path))
        monkeypatch.setattr(bench, "LEGS", ["nds_h"])
        monkeypatch.setattr(bench, "_probe_backend", lambda *a: "")
        bench.BANK.clear()
        with open(bench._dev_bank_path("nds_h"), "w") as f:
            json.dump({"rows": None, "times": {"1": 2.0}}, f)
        with open(bench._cpu_bank_path("nds_h"), "w") as f:
            json.dump({"rows": None, "times": {"1": 4.0}}, f)
        rc = bench.main()
        assert rc == bench.EXIT_STALE_METRIC
        out = capsys.readouterr().out.strip().splitlines()
        line = json.loads(out[-1])
        assert line["stale_device_times"] is True

    def test_no_bank_fails_too(self, tmp_path, monkeypatch, capsys):
        import bench
        monkeypatch.setattr(bench, "DATA_ROOT", str(tmp_path))
        monkeypatch.setattr(bench, "LEGS", ["nds_h"])
        monkeypatch.setattr(bench, "_probe_backend", lambda *a: "")
        bench.BANK.clear()
        rc = bench.main()
        assert rc == bench.EXIT_NO_METRIC
        out = capsys.readouterr().out.strip().splitlines()
        assert json.loads(out[-1])["device_unreachable"] is True


# --------------------------------------------------------- NDS115 rule

class TestBlockingInAsyncRule:
    def _lint(self, src, path="nds_tpu/serve/mod.py"):
        from nds_tpu.analysis.lint_rules import lint_sources
        return lint_sources({path: src}, enabled={"NDS115"})

    def test_flags_sleep_open_result(self):
        src = ("import time\n"
               "async def h(reader, fut):\n"
               "    time.sleep(1)\n"
               "    f = open('/tmp/x')\n"
               "    v = fut.result()\n"
               "    return f, v\n")
        res = self._lint(src)
        assert len(res.violations) == 3
        assert {v.line for v in res.violations} == {3, 4, 5}

    def test_sync_function_and_nested_def_are_clean(self):
        src = ("import time\n"
               "def sync():\n"
               "    time.sleep(1)\n"
               "async def h():\n"
               "    def helper():\n"
               "        return open('/tmp/x')\n"
               "    return helper\n")
        res = self._lint(src)
        assert res.violations == []

    def test_scoped_to_serve_package(self):
        src = ("import time\n"
               "async def h():\n"
               "    time.sleep(1)\n")
        res = self._lint(src, path="nds_tpu/engine/x.py")
        assert res.violations == []

    def test_waiver_honored(self):
        src = ("import time\n"
               "async def h():\n"
               "    time.sleep(1)  "
               "# ndslint: waive[NDS115] -- test fixture\n")
        res = self._lint(src)
        assert res.violations == [] and len(res.waived) == 1

    def test_serve_tree_is_clean(self):
        from nds_tpu.analysis.lint_rules import lint_sources
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        srcs = {}
        sdir = os.path.join(root, "nds_tpu", "serve")
        for f in os.listdir(sdir):
            if f.endswith(".py"):
                rel = f"nds_tpu/serve/{f}"
                srcs[rel] = open(os.path.join(sdir, f)).read()
        res = lint_sources(srcs, enabled={"NDS115"})
        assert res.violations == []

    def test_in_default_rules(self):
        from nds_tpu.analysis.lint_rules import default_rules
        assert any(r.id == "NDS115" for r in default_rules())
