"""Replicated serve fleet (nds_tpu/serve/fleet.py + replica.py):

- template_digest affinity keys: literal variants of one template
  share a digest, templates/suites split;
- RequestJournal accounting: accept/assign/settle, first-final-wins
  duplicate suppression, lost/double detection, atomic persistence;
- ndsload chaos schedule parsing + replica incarnation parsing +
  serve.net limit config;
- NDS118 ``undeadlined-await`` lint rule (fixtures + the real serve
  tree must be clean);
- the live-fleet contract (subprocess replicas): SIGTERM drain under
  ``engine.prefetch.boundary=on`` finishes every in-flight request,
  exits 75, resumes warm, and is re-admitted — with the router's
  journal clean throughout (zero lost, zero double-answered).
"""

import asyncio
import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

from nds_tpu.serve.fleet import RequestJournal, template_digest


# ---------------------------------------------------- affinity digest

class TestTemplateDigest:
    def test_literal_variants_share(self):
        a = template_digest(
            "nds_h", "select * from t where a > 42 and b = 'x'")
        b = template_digest(
            "nds_h", "select * from t where a > 7 and b = 'other'")
        assert a == b

    def test_templates_and_suites_split(self):
        base = template_digest("nds", "select a from t")
        assert template_digest("nds", "select b from t") != base
        assert template_digest("nds_h", "select a from t") != base

    def test_quoted_quote_stays_one_literal(self):
        a = template_digest("nds", "select * from t where x = 'a''b'")
        b = template_digest("nds", "select * from t where x = 'c'")
        assert a == b


# ----------------------------------------------------- request journal

class TestRequestJournal:
    def _mk(self, tmp_path):
        return RequestJournal(str(tmp_path / "journal.json"))

    def test_accept_settle_verify_clean(self, tmp_path):
        j = self._mk(tmp_path)
        j.accept("r-1", "tenant0", "nds", "q1", "abc")
        j.assign("r-1", "r0")
        out = j.settle("r-1", {"status": "ok", "digest": "d"})
        assert out["status"] == "ok"
        v = j.verify()
        assert v["accepted"] == 1 and v["settled"] == 1
        assert v["lost"] == [] and v["double"] == []

    def test_unsettled_is_lost(self, tmp_path):
        j = self._mk(tmp_path)
        j.accept("r-1", "t", "nds", "q1", None)
        j.accept("r-2", "t", "nds", "q2", None)
        j.settle("r-2", {"status": "ok"})
        assert j.verify()["lost"] == ["r-1"]

    def test_duplicate_settle_keeps_canonical(self, tmp_path):
        j = self._mk(tmp_path)
        j.accept("r-1", "t", "nds", "q1", None)
        first = j.settle("r-1", {"status": "ok", "digest": "first"})
        again = j.settle("r-1", {"status": "ok", "digest": "second"})
        # first final answer wins; the duplicate is returned AS the
        # canonical response, never surfaced to the caller
        assert first["digest"] == "first"
        assert again["digest"] == "first"
        assert j.verify()["double"] == ["r-1"]

    def test_late_settle_clears_lost(self, tmp_path):
        j = self._mk(tmp_path)
        j.accept("r-1", "t", "nds", "q1", None)
        assert j.verify()["lost"] == ["r-1"]
        j.settle("r-1", {"status": "ok"})
        assert j.verify()["lost"] == []

    def test_persists_across_reopen(self, tmp_path):
        path = str(tmp_path / "journal.json")
        j = RequestJournal(path)
        j.accept("r-1", "tenant0", "nds", "q1", "abc")
        j.assign("r-1", "r0")
        j.settle("r-1", {"status": "ok", "digest": "d"})
        with open(path) as f:
            doc = json.load(f)
        assert doc["accepted"]["r-1"]["assignments"] == ["r0"]
        assert doc["outcomes"]["r-1"]["status"] == "ok"
        # the full response body is NOT persisted (journal stays
        # small); the accounting fields are
        assert "response" not in doc["outcomes"]["r-1"]


# ------------------------------------------- chaos + replica parsing

class TestFleetParsing:
    def test_kill_schedule(self):
        import signal as sg

        import ndsload
        evs = ndsload.parse_kill_schedule(
            ["replica=1@2.5,TERM", "replica=r0@0.5"])
        assert [e["t"] for e in evs] == [0.5, 2.5]
        assert evs[0]["signal"] == int(sg.SIGKILL)
        assert evs[1]["signal"] == int(sg.SIGTERM)
        assert evs[1]["replica"] == "1"

    def test_kill_schedule_rejects_garbage(self):
        import ndsload
        with pytest.raises(ValueError):
            ndsload.parse_kill_schedule(["replica=r0"])
        with pytest.raises(ValueError):
            ndsload.parse_kill_schedule(["replica=r0@1,NOPE"])

    def test_parse_incarnation(self):
        from nds_tpu.serve.replica import parse_incarnation
        assert parse_incarnation(None) == 0
        assert parse_incarnation("r0") == 0
        assert parse_incarnation("r0#r3") == 3
        assert parse_incarnation("r0#rx") == 0

    def test_net_limits(self):
        from nds_tpu.serve.net import (
            DEFAULT_MAX_LINE_BYTES, DEFAULT_READ_TIMEOUT_S, net_limits,
        )
        from nds_tpu.utils.config import EngineConfig
        assert net_limits(None) == (DEFAULT_READ_TIMEOUT_S,
                                    DEFAULT_MAX_LINE_BYTES)
        cfg = EngineConfig(overrides={
            "serve.net.read_timeout_s": "5.5",
            "serve.net.max_line_bytes": "10",
        })
        t, n = net_limits(cfg)
        assert t == 5.5
        assert n == 1024  # floor: a limit below one frame is a DoS


# --------------------------------------------------------- NDS118 rule

class TestUndeadlinedAwaitRule:
    def _lint(self, src, path="nds_tpu/serve/mod.py"):
        from nds_tpu.analysis.lint_rules import lint_sources
        return lint_sources({path: src}, enabled={"NDS118"})

    def test_flags_bare_stream_awaits(self):
        src = ("import asyncio\n"
               "async def h(reader, writer):\n"
               "    line = await reader.readline()\n"
               "    await writer.drain()\n"
               "    r, w = await asyncio.open_connection('h', 1)\n"
               "    return line, r, w\n")
        res = self._lint(src)
        assert {v.line for v in res.violations} == {3, 4, 5}

    def test_wait_for_wrapped_is_clean(self):
        src = ("import asyncio\n"
               "async def h(reader, writer):\n"
               "    line = await asyncio.wait_for(\n"
               "        reader.readline(), timeout=5)\n"
               "    await asyncio.wait_for(writer.drain(), 2)\n"
               "    return line\n")
        assert self._lint(src).violations == []

    def test_timeout_block_is_clean(self):
        src = ("import asyncio\n"
               "async def h(reader):\n"
               "    async with asyncio.timeout(3):\n"
               "        return await reader.readline()\n")
        assert self._lint(src).violations == []

    def test_nested_coroutine_not_covered_by_outer_timeout(self):
        # the nested coroutine RUNS wherever it is awaited — the
        # enclosing block's deadline does not travel with it
        src = ("import asyncio\n"
               "async def outer(reader):\n"
               "    async with asyncio.timeout(3):\n"
               "        async def inner():\n"
               "            return await reader.readline()\n"
               "        return inner\n")
        res = self._lint(src)
        assert [v.line for v in res.violations] == [5]

    def test_non_stream_awaits_are_clean(self):
        src = ("import asyncio\n"
               "async def h(fut):\n"
               "    await asyncio.sleep(1)\n"
               "    return await fut\n")
        assert self._lint(src).violations == []

    def test_scoped_to_serve_package(self):
        src = ("async def h(reader):\n"
               "    return await reader.readline()\n")
        res = self._lint(src, path="nds_tpu/engine/x.py")
        assert res.violations == []

    def test_waiver_honored(self):
        src = ("async def h(reader):\n"
               "    return await reader.readline()  "
               "# ndslint: waive[NDS118] -- test fixture\n")
        res = self._lint(src)
        assert res.violations == [] and len(res.waived) == 1

    def test_serve_tree_is_clean(self):
        from nds_tpu.analysis.lint_rules import lint_sources
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        srcs = {}
        sdir = os.path.join(root, "nds_tpu", "serve")
        for f in os.listdir(sdir):
            if f.endswith(".py"):
                rel = f"nds_tpu/serve/{f}"
                srcs[rel] = open(os.path.join(sdir, f)).read()
        res = lint_sources(srcs, enabled={"NDS118"})
        assert res.violations == []

    def test_in_default_rules(self):
        from nds_tpu.analysis.lint_rules import default_rules
        assert any(r.id == "NDS118" for r in default_rules())


# ------------------------------------- single-replica boundary drain

class TestSingleReplicaDrain:
    """One replica, NO router: SIGTERM lands while requests are in
    flight on a live connection. Because there is no redelivery to
    mask a drop, every answer that arrives after the signal proves
    the drain FINISHED the in-flight work (including the
    boundary-overlapped request under engine.prefetch.boundary=on)
    before exiting 75."""

    def test_drain_finishes_inflight_then_exit_75(self, tmp_path):
        import json as _json
        import signal
        import subprocess

        import ndsload
        wd = str(tmp_path)
        argv = ndsload.fleet_replica_argv(wd, 0.01, max_queue=32,
                                          boundary="on")
        ann = os.path.join(wd, "announce.json")
        proc = subprocess.Popen(argv("solo", ann, 0))
        try:
            deadline = time.time() + 300
            while time.time() < deadline and not os.path.exists(ann):
                time.sleep(0.1)
            assert os.path.exists(ann), "replica never announced"
            with open(ann) as f:
                port = _json.load(f)["port"]
            rc = asyncio.run(self._drive(proc, port, signal.SIGTERM))
            assert rc == 75, f"drain exited {rc}, want 75"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    async def _drive(self, proc, port, sig):
        import ndsload
        from nds_tpu.serve.fleet import ReplicaClient
        client = ReplicaClient("solo", "127.0.0.1", port)
        await client.connect()
        try:
            warm = dict(ndsload.warmup_docs(3, (), (96,))[0],
                        id="warm-0")
            w = await client.request(warm, timeout=300)
            assert w.get("status") == "ok", w

            docs = [dict(d, id=f"t-{i}") for i, d in enumerate(
                ndsload.build_requests(4, 5, tenants=1,
                                       nds_h_templates=(),
                                       nds_templates=(96,)))]
            tasks = [asyncio.ensure_future(
                client.request(d, timeout=120)) for d in docs]
            # let them reach the engine queue, then signal mid-flight
            await asyncio.sleep(0.3)
            proc.send_signal(sig)
            resp = await asyncio.gather(*tasks)
            for r in resp:
                assert r.get("status") == "ok", r
        finally:
            await client.close()
        return await asyncio.get_running_loop().run_in_executor(
            None, proc.wait)


# ------------------------------------------- live fleet drain/resume

class TestFleetDrainResume:
    """One real 2-replica fleet, replicas running with
    ``engine.prefetch.boundary=on``: a SIGTERM drain mid-load must
    finish every accepted request (including the boundary-overlapped
    one), exit 75, resume warm, and pass the health probe back into
    the ring — journal clean throughout."""

    def test_drain_resume_readmission(self, tmp_path):
        import ndsload
        from nds_tpu.serve.fleet import launch_fleet
        from nds_tpu.utils.config import EngineConfig

        wd = str(tmp_path)
        cfg = EngineConfig(overrides={
            "serve.max_queue": "32",
            "serve.fleet.max_pending": "128",
            "serve.fleet.ping_interval_s": "0.25",
            "serve.fleet.ping_timeout_s": "3",
        })
        sup, router = launch_fleet(
            os.path.join(wd, "fleet"), ["r0", "r1"],
            ndsload.fleet_replica_argv(wd, 0.01, max_queue=32,
                                       boundary="on"),
            config=cfg, stall_s=10.0)
        sup.start()
        try:
            summary = asyncio.run(self._drive(sup, router))
        finally:
            sup.stop()
        r0 = summary["replicas"]["r0"]
        assert 75 in r0["exit_codes"], r0
        assert r0["resumes"] == 1 and r0["restarts"] == 0, r0

    async def _drive(self, sup, router):
        import ndsload
        await router.start()
        try:
            assert await router.wait_admitted(2, 300), \
                f"never admitted: {router.healthy_replicas()}"
            warm = await ndsload.run_router(
                router, ndsload.warmup_docs(3, (1,), (96,)), 1)
            ws = ndsload.summarize(warm)
            assert ws["status"].get("ok") == len(warm), ws

            docs = ndsload.build_requests(
                10, 5, tenants=2, nds_h_templates=(1,),
                nds_templates=(96,))
            done = {"n": 0}

            async def one(doc):
                resp = await router.submit(doc)
                done["n"] += 1
                return resp

            async def drain_mid_load():
                while done["n"] < 2:
                    await asyncio.sleep(0.05)
                sup.drain("r0")

            results = await asyncio.gather(
                drain_mid_load(), *[one(d) for d in docs])
            resp = results[1:]
            ls = ndsload.summarize(resp)
            # the drain sheds nothing to the CALLER: departures are
            # redelivered by the router, in-flight work finishes on
            # the draining replica
            assert ls["status"].get("ok") == len(docs), ls
            v = router.journal.verify()
            assert not v["lost"] and not v["double"], v

            # exit 75 -> warm resume -> health probe -> re-admission
            deadline = time.time() + 240
            while time.time() < deadline:
                if "r0" in router.healthy_replicas():
                    break
                await asyncio.sleep(0.25)
            assert "r0" in router.healthy_replicas(), \
                router.healthy_replicas()
            post = await ndsload.run_router(
                router, ndsload.build_requests(
                    4, 9, tenants=1, nds_h_templates=(1,),
                    nds_templates=(96,)), 2)
            ps = ndsload.summarize(post)
            assert ps["status"].get("ok") == len(post), ps
        finally:
            await router.stop()
        return sup.summary()
