"""End-to-end drive of the L6 orchestrator (VERDICT r3 "next" #5).

Runs run_full_bench through EVERY phase — datagen (+2 refresh sets) ->
transcode -> stream gen (RNGSEED from the load report) -> power ->
throughput x2 -> maintenance x2 -> composite metric — at a tiny scale
on the cpu backend, then asserts the metric was computed from all four
real terms and the inter-phase report plumbing held together
(`nds/nds_bench.py:367-498` semantics).
"""

import csv
import os

import pytest

from nds_tpu.nds.bench import run_full_bench

pytestmark = pytest.mark.slow


def test_full_bench_end_to_end(tmp_path):
    work = tmp_path / "bench_work"
    cfg = {
        "scale_factor": 0.01,
        "parallel": 2,
        "num_streams": 1,       # -> 3 streams: power + 1 per half
        "backend": "cpu",
        "paths": {
            "raw_data": str(work / "raw"),
            "refresh_data": str(work / "refresh"),
            "warehouse": str(work / "wh"),
            "streams": str(work / "streams"),
            "reports": str(work / "reports"),
        },
        "skip": {},
    }
    metrics = run_full_bench(cfg)

    # all four terms present and positive
    assert metrics["load_time_s"] > 0
    assert metrics["power_time_s"] > 0
    assert len(metrics["throughput_times_s"]) == 2
    assert all(t > 0 for t in metrics["throughput_times_s"])
    assert len(metrics["maintenance_times_s"]) == 2
    assert all(t > 0 for t in metrics["maintenance_times_s"])
    assert metrics["metric"] is not None and metrics["metric"] > 0

    # metrics.csv carries the full row the composite was derived from
    with open(os.path.join(cfg["paths"]["reports"], "metrics.csv")) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 1
    r = rows[0]
    for col in ("load_s", "power_s", "throughput1_s", "throughput2_s",
                "maintenance1_s", "maintenance2_s"):
        assert float(r[col]) > 0, col
    assert int(r["metric"]) == metrics["metric"]

    # phase artifacts exist: per-query JSON summaries + stream files
    json_dir = os.path.join(cfg["paths"]["reports"], "json")
    assert len(os.listdir(json_dir)) >= 99
    assert sorted(os.listdir(cfg["paths"]["streams"]))[:2] == [
        "query_0.sql", "query_1.sql"]
