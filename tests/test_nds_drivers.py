"""NDS phase-driver tests: gen_data -> transcode -> streams -> power ->
validate, end to end at tiny scale (the CI analog of the reference's
manual pipeline, `nds/README.md:136-508`), plus refresh datagen, the
NULL round-trip through raw text and parquet, and the config layer."""

import json
import os

import numpy as np
import pytest

from nds_tpu.datagen import tpcds, tpcds_refresh
from nds_tpu.io import csv_io
from nds_tpu.nds import gen_data, streams, transcode, validate
from nds_tpu.nds.schema import (
    get_maintenance_schemas, get_schemas, table_rows,
)
from nds_tpu.utils import power_core
from nds_tpu.utils.config import EngineConfig

SF = 0.01
SUBSET = ["query96", "query7", "query93"]


@pytest.fixture(scope="module")
def pipeline(tmp_path_factory):
    """Run datagen + transcode once; yield dir paths."""
    root = tmp_path_factory.mktemp("nds_pipe")
    raw = str(root / "raw")
    wh = str(root / "wh")
    report = str(root / "load_report.txt")
    gen_data.generate_data_local(SF, 2, raw, workers=2)
    transcode.transcode(raw, wh, report)
    sdir = str(root / "streams")
    streams.generate_query_streams(sdir, 1)
    return {"raw": raw, "wh": wh, "report": report,
            "stream": os.path.join(sdir, "query_0.sql"),
            "root": str(root)}


class TestGenData:
    def test_chunk_files_layout(self, pipeline):
        # chunked fact -> per-table dir with _step_parallel names
        files = os.listdir(os.path.join(pipeline["raw"], "store_sales"))
        assert sorted(files) == ["store_sales_1_2.dat",
                                 "store_sales_2_2.dat"]
        # fixed dim -> single chunk
        assert os.listdir(os.path.join(pipeline["raw"], "date_dim")) == [
            "date_dim.dat"]

    def test_raw_roundtrip_with_nulls(self, pipeline):
        """dsdgen NULL convention (empty field) survives write+read."""
        schema = get_schemas()["store_sales"]
        paths = [os.path.join(pipeline["raw"], "store_sales", f)
                 for f in sorted(os.listdir(
                     os.path.join(pipeline["raw"], "store_sales")))]
        t = csv_io.read_tbl(paths, "store_sales", schema)
        gen = tpcds.gen_table("store_sales", SF)
        mask = gen["ss_customer_sk#null"]
        assert not mask.all()
        col = t.column("ss_customer_sk")
        assert col.null_mask is not None
        assert int((~col.null_mask).sum()) == int((~mask).sum())

    def test_parquet_roundtrip_with_nulls(self, pipeline, tmp_path):
        schema = get_schemas()["store_sales"]
        t = csv_io.read_tbl(
            [os.path.join(pipeline["raw"], "store_sales",
                          "store_sales_1_2.dat")], "store_sales", schema)
        p = str(tmp_path / "ss.parquet")
        csv_io.write_parquet(t, p)
        back = csv_io.read_parquet([p], "store_sales", schema)
        for cname in ("ss_customer_sk", "ss_sold_date_sk"):
            a, b = t.column(cname), back.column(cname)
            assert (a.null_mask is None) == (b.null_mask is None)
            if a.null_mask is not None:
                assert np.array_equal(a.null_mask, b.null_mask)
                assert np.array_equal(a.values[a.null_mask],
                                      b.values[b.null_mask])


class TestRefreshData:
    def test_all_maintenance_tables_generate(self):
        schemas = get_maintenance_schemas()
        for t, schema in schemas.items():
            arrays = tpcds_refresh.gen_refresh_table(t, SF, 1)
            assert set(schema.names) <= set(arrays), t
            n = len(arrays[schema.names[0]])
            assert n >= 1, t

    def test_lineitems_reference_orders(self):
        o = tpcds_refresh.gen_refresh_table("s_purchase", SF, 1)
        li = tpcds_refresh.gen_refresh_table("s_purchase_lineitem", SF, 1)
        assert np.isin(li["plin_purchase_id"],
                       o["purc_purchase_id"]).all()

    def test_item_ids_join_current_scd_records(self):
        li = tpcds_refresh.gen_refresh_table("s_purchase_lineitem", SF, 1)
        item = tpcds.gen_table("item", SF)
        # current record = rec_end_date NULL (mask False = null)
        current = item["i_item_id"][~item["i_rec_end_date#null"]]
        assert np.isin(li["plin_item_id"], current).all()

    def test_updates_differ_and_are_deterministic(self):
        a1 = tpcds_refresh.gen_refresh_table("s_purchase", SF, 1)
        a2 = tpcds_refresh.gen_refresh_table("s_purchase", SF, 2)
        b1 = tpcds_refresh.gen_refresh_table("s_purchase", SF, 1)
        assert not np.array_equal(a1["purc_purchase_id"],
                                  a2["purc_purchase_id"])
        assert np.array_equal(a1["purc_customer_id"],
                              b1["purc_customer_id"])

    def test_delete_window_inside_base_dates(self):
        d = tpcds_refresh.gen_refresh_table("delete", SF, 1)
        lo = tpcds.sk_to_epoch(tpcds.SALES_DATE_LO)
        hi = tpcds.sk_to_epoch(tpcds.SALES_DATE_HI)
        assert lo <= d["date1"][0] <= d["date2"][0] <= hi

    def test_gen_data_update_cli(self, tmp_path):
        out = str(tmp_path / "refresh1")
        gen_data.generate_refresh_data(SF, 1, out)
        assert os.path.isfile(
            os.path.join(out, "s_purchase", "s_purchase.dat"))
        schema = get_maintenance_schemas()["s_purchase"]
        t = csv_io.read_tbl(
            [os.path.join(out, "s_purchase", "s_purchase.dat")],
            "s_purchase", schema)
        assert t.nrows >= 8


class TestTranscode:
    def test_partitioned_layout(self, pipeline):
        ssdir = os.path.join(pipeline["wh"], "store_sales")
        parts = os.listdir(ssdir)
        assert any(p.startswith("ss_sold_date_sk=") for p in parts)

    def test_rngseed_and_load_time(self, pipeline):
        assert transcode.get_rngseed(pipeline["report"]) > 0
        assert transcode.get_load_time(pipeline["report"]) > 0

    def test_update_mode(self, pipeline, tmp_path):
        refresh_raw = str(tmp_path / "refresh_raw")
        gen_data.generate_refresh_data(SF, 1, refresh_raw)
        wh2 = str(tmp_path / "wh2")
        rep = str(tmp_path / "rep.txt")
        transcode.transcode(refresh_raw, wh2, rep, update=True)
        assert os.path.isdir(os.path.join(wh2, "s_purchase"))

    def test_drifted_report_raises(self, tmp_path):
        """Anchored parse: a report whose header drifted must raise, not
        return a silently-wrong float."""
        bad = str(tmp_path / "bad.txt")
        with open(bad, "w") as f:
            f.write("Conversion finished in about 12s maybe\n")
        with pytest.raises(ValueError):
            transcode.get_load_time(bad)
        with pytest.raises(ValueError):
            transcode.get_rngseed(bad)

    def test_orc_warehouse_end_to_end(self, pipeline, tmp_path):
        """--output_format orc -> power --input_format orc matches the
        parquet-warehouse results (`nds/nds_transcode.py:69-152` format
        breadth)."""
        wh_orc = str(tmp_path / "wh_orc")
        rep = str(tmp_path / "rep_orc.txt")
        tables = ["store_sales", "date_dim", "time_dim", "store",
                  "household_demographics"]
        transcode.transcode(pipeline["raw"], wh_orc, rep, tables=tables,
                            output_format="orc")
        ssdir = os.path.join(wh_orc, "store_sales")
        assert any(f.endswith(".orc") for _r, _d, fs in os.walk(ssdir)
                   for f in fs)
        from nds_tpu.nds.power import SUITE
        cfg = EngineConfig(overrides={"engine.backend": "cpu"})
        sess_orc = power_core.make_session(SUITE, cfg)
        power_core.load_warehouse(SUITE, sess_orc, wh_orc, "orc",
                                  tables=tables)
        sess_pq = power_core.make_session(SUITE, cfg)
        power_core.load_warehouse(SUITE, sess_pq, pipeline["wh"],
                                  "parquet", tables=tables)
        sql = streams.render_query(96)
        exp = sess_pq.sql(sql).to_pandas()
        got = sess_orc.sql(sql).to_pandas()
        assert got.equals(exp)


class TestPowerRun:
    def test_cpu_power_subset_and_validate(self, pipeline, tmp_path):
        out1 = str(tmp_path / "o1")
        out2 = str(tmp_path / "o2")
        jsons = str(tmp_path / "json")
        from nds_tpu.nds.power import SUITE
        cfg = EngineConfig(overrides={"engine.backend": "cpu"})
        for out in (out1, out2):
            failures = power_core.run_query_stream(
                SUITE, pipeline["wh"], pipeline["stream"],
                str(tmp_path / "time.csv"), config=cfg,
                json_summary_folder=jsons, output_prefix=out,
                query_subset=SUBSET)
            assert failures == 0
        unmatched = validate.iterate_queries(out1, out2,
                                             pipeline["stream"])
        assert unmatched == []
        # JSON summary contract: engineConf reflects the config layer
        jfiles = sorted(os.listdir(jsons))
        assert jfiles
        with open(os.path.join(jsons, jfiles[0])) as f:
            summary = json.load(f)
        assert summary["env"]["engineConf"]["engine.backend"] == "cpu"
        assert summary["queryStatus"] == ["Completed"]

    def test_extra_time_log(self, pipeline, tmp_path):
        """--extra_time_log writes a second identical copy of the CSV
        time log (`nds/nds_power.py:305-308`)."""
        from nds_tpu.nds.power import SUITE
        cfg = EngineConfig(overrides={"engine.backend": "cpu"})
        tlog = str(tmp_path / "t.csv")
        extra = str(tmp_path / "remote" / "t_extra.csv")
        failures = power_core.run_query_stream(
            SUITE, pipeline["wh"], pipeline["stream"], tlog, config=cfg,
            query_subset=["query96"], extra_time_log=extra)
        assert failures == 0
        assert open(extra).read() == open(tlog).read()

    def test_failure_never_aborts_the_stream(self, pipeline, tmp_path):
        """The reference runs every query regardless of failures; only
        the exit code reflects them (`nds/nds_power.py:255-283,391-393`).
        --allow_failure is exit-code-only, handled by the driver mains."""
        from nds_tpu.nds.power import SUITE
        bad_stream = str(tmp_path / "bad_stream.sql")
        good = streams.render_query(96)
        with open(bad_stream, "w") as f:
            f.write("-- start query 1 in stream 0 using template "
                    "query98.tpl\nselect broken syntax from nowhere\n"
                    "-- end query 1 in stream 0 using template "
                    "query98.tpl\n\n"
                    "-- start query 2 in stream 0 using template "
                    "query96.tpl\n" + good + "\n"
                    "-- end query 2 in stream 0 using template "
                    "query96.tpl\n")
        cfg = EngineConfig(overrides={"engine.backend": "cpu"})
        jsons = str(tmp_path / "json")
        tlog = str(tmp_path / "t.csv")
        failures = power_core.run_query_stream(
            SUITE, pipeline["wh"], bad_stream, tlog, config=cfg,
            json_summary_folder=jsons)
        assert failures == 1
        assert "query96" in open(tlog).read()  # ran past the failure
        # the failed query's summary records the Failed status + exception
        failed = [f for f in os.listdir(jsons) if "query98" in f]
        with open(os.path.join(jsons, failed[0])) as f:
            summary = json.load(f)
        assert summary["queryStatus"] == ["Failed"]
        assert summary["exceptions"]


class TestStreamRebinding:
    def test_streams_rebind_parameters(self, tmp_path):
        """dsqgen -rngseed semantics (`nds/nds_gen_query_stream.py:42-89`):
        every stream redraws its substitution parameters, so stream 1 is
        a different workload from stream 0."""
        import random
        rng0 = random.Random(17 * 7919 + 0)
        rng1 = random.Random(17 * 7919 + 1)
        p0 = {qn: streams.random_params(qn, rng0, 0)
              for qn in streams.available_templates()}
        p1 = {qn: streams.random_params(qn, rng1, 1)
              for qn in streams.available_templates()}
        differing = [qn for qn in p0 if p0[qn] != p1[qn]]
        # templates with >= 2 parameter slots essentially always differ
        assert len(differing) > 80
        # and the rendered stream files differ too
        sdir = str(tmp_path / "s")
        paths = streams.generate_query_streams(
            sdir, 2, rng_seed=17, templates=[7, 21, 34],
            qualification=False)
        with open(paths[0]) as f0, open(paths[1]) as f1:
            assert f0.read() != f1.read()

    def test_qualification_default_is_stable(self, tmp_path):
        sdir = str(tmp_path / "s")
        a = streams.generate_query_streams(sdir, 1, templates=[7])
        with open(a[0]) as f:
            body = f.read()
        assert streams.render_query(7) in body

    def test_rebound_params_render_and_plan(self):
        """Every drawn binding must render to SQL the frontend plans."""
        import random
        from nds_tpu.engine.session import Session
        sess = Session.for_nds()
        rng = random.Random(99)
        for qn in streams.available_templates():
            sql = streams.render_query(
                qn, streams.random_params(qn, rng, 1))
            for stmt in [s for s in sql.split(";") if s.strip()]:
                sess.plan(stmt)


class TestStreamStatementParity:
    def test_stream_carries_103_statements(self, tmp_path):
        """The reference runs 103 executable statements per stream, not
        99: templates 14/23/24/39 are two-statement and split into
        _part1/_part2 (`nds/nds_gen_query_stream.py:91-103`,
        `nds/nds_power.py:50-77`)."""
        sdir = str(tmp_path / "s")
        paths = streams.generate_query_streams(sdir, 1, rng_seed=31)
        qd = streams.parse_query_stream(paths[0])
        assert len(qd) == 103
        for qn in (14, 23, 24, 39):
            assert f"query{qn}_part1" in qd and f"query{qn}_part2" in qd
            assert f"query{qn}" not in qd
            # the two parts are distinct statements, not a re-split of one
            assert qd[f"query{qn}_part1"] != qd[f"query{qn}_part2"]
        # every other template contributes exactly one statement
        singles = [k for k in qd if "_part" not in k]
        assert len(singles) == 95

    def test_both_parts_plan(self):
        """Both statements of each two-part template must get through
        the frontend (planner), not just the first."""
        from nds_tpu.engine.session import Session
        sess = Session.for_nds()
        for qn in (14, 23, 24, 39):
            stmts = [s for s in streams.render_query(qn).split(";")
                     if s.strip()]
            assert len(stmts) == 2
            for stmt in stmts:
                sess.plan(stmt)


class TestThroughputInProcess:
    def test_one_chip_time_sharing(self, pipeline, tmp_path):
        """The single-process multi-stream mode: one warehouse load, one
        shared session, round-robin interleave, per-stream reference-
        format time logs (resource-splitting story for one TPU chip,
        `nds/README.md:530-535`)."""
        from nds_tpu.nds.throughput import run_streams_inprocess
        from nds_tpu.utils.timelog import TimeLog
        sdir = str(tmp_path / "streams")
        paths = streams.generate_query_streams(
            sdir, 2, rng_seed=7, templates=[96, 7, 93],
            qualification=False)
        out = str(tmp_path / "tp")
        elapse, failures = run_streams_inprocess(
            pipeline["wh"], paths, out, backend="cpu")
        assert elapse > 0 and failures == [0, 0]
        for i in range(2):
            rows = list(TimeLog.read(
                os.path.join(out, f"query_{i}_time.csv")))
            names = [q for _a, q, _ms in rows]
            # stream 1 is permuted (stream_order), so compare as sets
            assert set(names[:3]) == {"query96", "query7", "query93"}
            assert names[-1] == "Power Test Time"


class TestConfigLayer:
    def test_template_and_property_precedence(self, tmp_path):
        tpl = tmp_path / "t.template"
        tpl.write_text("engine.backend=cpu\nengine.floats=false\n")
        prop = tmp_path / "p.properties"
        prop.write_text("engine.floats=true\n")
        cfg = EngineConfig(str(tpl), str(prop))
        assert cfg.get("engine.backend") == "cpu"
        assert cfg.get_bool("engine.floats") is True

    def test_env_substitution(self, tmp_path, monkeypatch):
        tpl = tmp_path / "t.template"
        tpl.write_text("engine.backend=${MY_BACKEND:-cpu}\n")
        cfg = EngineConfig(str(tpl))
        assert cfg.get("engine.backend") == "cpu"
        monkeypatch.setenv("MY_BACKEND", "tpu")
        cfg = EngineConfig(str(tpl))
        assert cfg.get("engine.backend") == "tpu"

    def test_shipped_templates_parse(self):
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for f in os.listdir(os.path.join(here, "configs")):
            if f.endswith((".template", ".properties")):
                EngineConfig(os.path.join(here, "configs", f))

    def test_make_session_floats_mode(self):
        from nds_tpu.nds.power import SUITE
        cfg = EngineConfig(overrides={"engine.backend": "cpu",
                                      "engine.floats": "true"})
        sess = power_core.make_session(SUITE, cfg)
        f = sess.catalog.schemas["store_sales"].field("ss_list_price")
        assert f.dtype.name.startswith("float")
        # table LOADING must agree with the catalog on decimal-vs-float
        loaded = power_core.suite_schemas(SUITE, cfg)
        assert loaded["store_sales"].field(
            "ss_list_price").dtype.name.startswith("float")

    def test_template_backend_not_trampled_by_default(self, tmp_path):
        """A template's engine.backend wins when --backend is absent;
        an explicit --backend still overrides it."""
        import types
        tpl = tmp_path / "t.template"
        tpl.write_text("engine.backend=cpu\n")
        args = types.SimpleNamespace(template=str(tpl),
                                     property_file=None, backend=None)
        cfg = power_core.config_from_args(args)
        assert cfg.get("engine.backend") == "cpu"
        args.backend = "tpu"
        cfg = power_core.config_from_args(args)
        assert cfg.get("engine.backend") == "tpu"
        # no layer sets it -> the driver default applies
        args = types.SimpleNamespace(template=None, property_file=None,
                                     backend=None)
        assert power_core.config_from_args(args).get(
            "engine.backend") == "tpu"


def test_source_table_count():
    # 24 generated tables + dbgen_version handled as metadata
    assert len(get_schemas()) == 24
    assert table_rows("store_sales", 1.0) == 2_880_404


class TestToolwrap:
    """External-tool wrapper mechanics (the TPC binaries stay external;
    these test the parts we own: patching and file layout)."""

    def test_apply_patches_idempotent(self, tmp_path):
        from nds_tpu.datagen import toolwrap
        src = tmp_path / "tools"
        src.mkdir()
        (src / "a.txt").write_text("line one\nline two\n")
        patches = tmp_path / "patches"
        patches.mkdir()
        (patches / "fix.patch").write_text(
            "--- a/a.txt\n+++ b/a.txt\n@@ -1,2 +1,2 @@\n line one\n"
            "-line two\n+line 2\n")
        applied = toolwrap.apply_patches(str(src), str(patches))
        assert applied == ["fix.patch"]
        assert "line 2" in (src / "a.txt").read_text()
        # second application is a no-op, not a failure
        applied = toolwrap.apply_patches(str(src), str(patches))
        assert (src / "a.txt").read_text().count("line 2") == 1

    def test_move_into_table_dirs(self, tmp_path):
        from nds_tpu.datagen.toolwrap import _move_into_table_dirs
        d = tmp_path / "data"
        d.mkdir()
        for f in ("store_sales_1_4.dat", "store_sales_2_4.dat",
                  "date_dim.dat", "lineitem.tbl.3", "web_site_1_4.dat"):
            (d / f).write_text("x|\n")
        _move_into_table_dirs(str(d))
        assert sorted(os.listdir(d / "store_sales")) == [
            "store_sales_1_4.dat", "store_sales_2_4.dat"]
        assert os.listdir(d / "date_dim") == ["date_dim.dat"]
        assert os.listdir(d / "lineitem") == ["lineitem.tbl.3"]
        assert os.listdir(d / "web_site") == ["web_site_1_4.dat"]


class TestToolwrapGolden:
    """Golden-fixture tests for the licensed-tool command lines: a fake
    tool binary records argv + env, and the recorded invocations are
    compared verbatim against the reference's drive commands
    (`nds/tpcds-gen/src/.../GenTable.java:233-279`,
    `nds-h/nds_h_gen_data.py:90-95`, `nds/nds_gen_query_stream.py:57-88`).
    The real binaries are licensed and never vendored; these tests pin
    the exact contract we'd drive them with."""

    @staticmethod
    def _fake_tool(tmp_path, name, emit=""):
        tool = tmp_path / "tools" / name
        tool.parent.mkdir(parents=True, exist_ok=True)
        rec = tmp_path / f"{name}_calls.txt"
        tool.write_text(
            "#!/bin/sh\n"
            f"echo \"$0 $@\" >> {rec}\n"
            f"echo \"DSS_PATH=$DSS_PATH DSS_QUERY=$DSS_QUERY\" >> "
            f"{rec}.env\n" + emit)
        tool.chmod(0o755)
        return str(tool), rec

    def _calls(self, rec):
        return [line.split()[1:] for line in
                sorted(rec.read_text().strip().splitlines())]

    def test_dsdgen_parallel_chunks(self, tmp_path):
        from nds_tpu.datagen import toolwrap
        d = str(tmp_path / "out")
        tool, rec = self._fake_tool(
            tmp_path, "dsdgen",
            emit=f'for c in 1 2 3 4; do : ; done\n'
                 f'touch {d}/store_sales_$$.dat\n')
        toolwrap.run_dsdgen(tool, scale=10, parallel=4, data_dir=d)
        calls = self._calls(rec)
        assert len(calls) == 4
        expect = [["-scale", "10", "-dir", d, "-force", "Y",
                   "-parallel", "4", "-child", str(c)]
                  for c in range(1, 5)]
        assert sorted(calls) == sorted(expect)
        # flat .dat files were moved into per-table dirs
        assert os.path.isdir(os.path.join(d, "store_sales"))

    def test_dsdgen_single_and_update(self, tmp_path):
        from nds_tpu.datagen import toolwrap
        d = str(tmp_path / "out")
        tool, rec = self._fake_tool(tmp_path, "dsdgen")
        toolwrap.run_dsdgen(tool, scale=1, parallel=1, data_dir=d,
                            update=2)
        (call,) = self._calls(rec)
        # single-process: no -parallel/-child; refresh set via -update
        assert call == ["-scale", "1", "-dir", d, "-force", "Y",
                        "-update", "2"]

    def test_dbgen_chunks_and_env(self, tmp_path):
        from nds_tpu.datagen import toolwrap
        d = str(tmp_path / "out")
        tool, rec = self._fake_tool(tmp_path, "dbgen")
        toolwrap.run_dbgen(tool, scale=1, parallel=2, data_dir=d)
        calls = self._calls(rec)
        assert sorted(calls) == [["-s", "1", "-f", "-C", "2", "-S", "1"],
                                 ["-s", "1", "-f", "-C", "2", "-S", "2"]]
        # dbgen writes where DSS_PATH points
        env = (tmp_path / "dbgen_calls.txt.env").read_text()
        assert f"DSS_PATH={d}" in env

    def test_dsqgen_stream_command(self, tmp_path):
        from nds_tpu.datagen import toolwrap
        tdir, out = str(tmp_path / "tpl"), str(tmp_path / "q")
        os.makedirs(tdir)
        tool, rec = self._fake_tool(tmp_path, "dsqgen")
        toolwrap.run_dsqgen(tool, tdir, out, scale=100, streams=4,
                            rngseed=19620718)
        (call,) = self._calls(rec)
        assert call == [
            "-template_dir", tdir,
            "-input", os.path.join(tdir, "templates.lst"),
            "-scale", "100", "-directory", tdir,
            "-dialect", "spark", "-output_dir", out,
            "-streams", "4", "-rngseed", "19620718"]

    def test_qgen_streams_capture_stdout(self, tmp_path):
        from nds_tpu.datagen import toolwrap
        qd, out = str(tmp_path / "queries"), str(tmp_path / "s")
        tool, rec = self._fake_tool(tmp_path, "qgen",
                                    emit='echo "select 1;"\n')
        toolwrap.run_qgen(tool, qd, out, scale=1, streams=2)
        calls = self._calls(rec)
        assert sorted(calls) == [["-s", "1"], ["-s", "1", "-p", "1"]]
        env = (tmp_path / "qgen_calls.txt.env").read_text()
        assert f"DSS_QUERY={qd}" in env
        for i in range(2):
            body = open(os.path.join(out, f"stream_{i}.sql")).read()
            assert "select 1;" in body

    def test_fan_out_failure_raises(self, tmp_path):
        from nds_tpu.datagen import toolwrap
        d = str(tmp_path / "out")
        tool = tmp_path / "tools" / "dsdgen"
        tool.parent.mkdir(parents=True, exist_ok=True)
        tool.write_text("#!/bin/sh\nexit 3\n")
        tool.chmod(0o755)
        with pytest.raises(toolwrap.ToolError):
            toolwrap.run_dsdgen(str(tool), scale=1, parallel=2,
                                data_dir=d)


def test_external_dsqgen_streams(tmp_path):
    """The licensed-tool path (`toolwrap.run_dsqgen`): exercised only
    when a built dsdgen/dsqgen kit is present. Recorded as SKIPPED when
    absent — the TPC tools are licensed and never vendored
    (SURVEY.md §2.4 licensing note)."""
    from nds_tpu.datagen import toolwrap
    tools = os.environ.get("NDS_TPCDS_TOOLS")
    dsqgen = os.path.join(tools, "dsqgen") if tools else None
    if not (dsqgen and os.path.isfile(dsqgen)):
        pytest.skip("licensed TPC-DS toolkit not present "
                    "(set NDS_TPCDS_TOOLS to its tools/ dir)")
    out = str(tmp_path / "streams")
    toolwrap.run_dsqgen(dsqgen, os.path.join(tools, "..", "query_templates"),
                        out, scale=1, streams=2)
    assert os.path.isfile(os.path.join(out, "query_0.sql"))


def test_dbgen_version_layout(tmp_path):
    """dbgen_version (the 25th source table) is emitted for layout
    parity with `nds/nds_gen_data.py:51` but has no query schema."""
    out = str(tmp_path / "raw")
    gen_data.generate_data_local(SF, 2, out, workers=1)
    p = os.path.join(out, "dbgen_version", "dbgen_version.dat")
    assert os.path.isfile(p)
    assert open(p).read().count("|") == 4
    assert "dbgen_version" not in get_schemas()
