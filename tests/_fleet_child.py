"""Child process for the fleet observability integration tests.

Two ranks join a jax.distributed world (the tests/_multihost_child.py
launch contract), each with an ARTIFICIALLY skewed export clock
(obs/trace._shift_epoch_offset — simulating hosts whose wall clocks
disagree), and exercise the fleet layer end-to-end. Queries execute on
each rank's OWN devices — this jaxlib's CPU backend cannot compile
cross-process XLA programs, and the fleet layer (handshake, shards,
sidecars, merge) is deliberately backend-free: it rides the
coordination service, exactly what lets it span worlds the compiler
cannot. Per-query coordination barriers stand in for the implicit
pairing a real pod's collectives provide.

- ``session`` mode (tests/test_fleet.py): in-memory NDS-H tables, a
  rank-local distributed session, and a handful of queries under
  power-loop-style ``query`` root spans with a fleet barrier before
  each — the parent merges the shards and asserts the paired spans
  overlap only AFTER clock alignment.

- ``power`` mode (tools/fleet_check.py): a real NDS-H power run
  (``power_core.run_query_stream``) over a raw warehouse the parent
  generated, with a watchdog armed, a ``stream.query:hang`` injected
  via the environment (both ranks hang at the same query), and an
  explicit-query profile trigger — the parent asserts the stall
  reports point at flight dumps + XLA captures and that ``ndsreport
  analyze`` renders the clock-aligned fleet timeline with straggler
  attribution.

argv: port rank nproc ndev workdir skew_s mode
"""

import os
import sys


def setup(port: str, pid: int, nproc: int, ndev: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["NDS_TPU_PLATFORM"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={ndev}"
    ).strip()
    os.environ.setdefault("JAX_ENABLE_X64", "true")
    os.environ["NDS_TPU_COORDINATOR"] = f"localhost:{port}"
    os.environ["NDS_TPU_NUM_PROCESSES"] = str(nproc)
    os.environ["NDS_TPU_PROCESS_ID"] = str(pid)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_session(workdir: str, pid: int, skew_s: float) -> None:
    """Rank-local distributed session + manual query root spans: the
    minimal surface the clock-alignment merge needs."""
    import jax

    from nds_tpu.datagen import tpch
    from nds_tpu.engine.session import Session
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds_h import streams
    from nds_tpu.nds_h.schema import get_schemas
    from nds_tpu.obs import fleet as obs_fleet
    from nds_tpu.obs import trace as obs_trace
    from nds_tpu.parallel import multihost
    from nds_tpu.parallel.dist_exec import make_distributed_factory
    from nds_tpu.parallel.mesh import make_mesh

    run_dir = os.path.join(workdir, "run")
    os.makedirs(run_dir, exist_ok=True)
    # artificial per-rank clock skew BEFORE the handshake: the
    # handshake must measure (and the merge must undo) exactly this
    obs_trace._shift_epoch_offset(pid * skew_s)
    os.environ[obs_trace.TRACE_ENV] = os.path.join(run_dir,
                                                   "trace.jsonl")
    assert multihost.maybe_initialize(), "distributed init did not run"
    meta = obs_fleet.init_fleet(run_dir, distributed=True)
    assert meta is not None and meta["world"] == 2, meta
    assert meta["aligned"], "clock handshake failed"

    # rank-LOCAL mesh: each rank executes on its own virtual devices
    # (see module docstring); the fleet layer is what spans the world
    mesh = make_mesh(devices=jax.local_devices())
    schemas = get_schemas()
    raw = {t: tpch.gen_table(t, 0.005) for t in schemas}
    s = Session.for_nds_h(make_distributed_factory(
        mesh=mesh, shard_threshold=500, multiprocess=False))
    for t in schemas:
        s.register_table(from_arrays(t, schemas[t], raw[t]))

    tracer = obs_trace.get_tracer()
    for qn in (1, 6, 3):
        # pair the ranks the way a pod's collectives would: both
        # enter the query together
        assert multihost.barrier(f"nds_tpu/test/q{qn}"), "barrier"
        got = None
        with tracer.span("query", query=f"q{qn}", suite="nds_h",
                         backend="distributed"):
            for stmt in streams.statements(qn):
                r = s.sql(stmt)
                got = r if r is not None else got
        assert got is not None and len(got.to_pandas()) >= 0
        print(f"rank {pid}: q{qn} OK", flush=True)
    tracer.flush_exports()
    print(f"FLEET_OK rank={pid}", flush=True)


def run_power(workdir: str, pid: int, skew_s: float) -> None:
    """Real NDS-H power run inside a 2-process world: the fleet
    wiring runs exactly where production runs it (power_core)."""
    from nds_tpu.nds_h.power import SUITE
    from nds_tpu.obs import trace as obs_trace
    from nds_tpu.parallel import multihost
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig

    run_dir = os.path.join(workdir, "run")
    os.makedirs(run_dir, exist_ok=True)
    obs_trace._shift_epoch_offset(pid * skew_s)
    os.environ[obs_trace.TRACE_ENV] = os.path.join(run_dir,
                                                   "trace.jsonl")
    assert multihost.maybe_initialize(), "distributed init did not run"
    cfg = EngineConfig(overrides={
        # device placement on this rank's own devices (the CPU
        # backend cannot compile cross-process programs; the fleet
        # layer is what spans the world)
        "engine.backend": "tpu",
        "engine.watchdog.stall_s": "2",
        "engine.retry.base_delay_s": "0.01",
        "engine.profile.dir": os.path.join(workdir, "prof"),
        "engine.profile.mode": "query1",
    })
    failures = power_core.run_query_stream(
        SUITE, os.path.join(workdir, "raw"),
        os.path.join(workdir, "streams", "stream_0.sql"),
        os.path.join(run_dir, f"time_r{pid}.csv"), config=cfg,
        input_format="raw", json_summary_folder=run_dir,
        query_subset=["query1", "query6", "query3"])
    assert failures == 0, f"rank {pid}: {failures} queries failed"
    print(f"FLEET_OK rank={pid}", flush=True)


def main() -> None:
    port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    ndev = int(sys.argv[4])
    workdir = sys.argv[5]
    skew_s = float(sys.argv[6])
    mode = sys.argv[7] if len(sys.argv) > 7 else "session"
    setup(port, pid, nproc, ndev)
    if mode == "power":
        run_power(workdir, pid, skew_s)
    else:
        run_session(workdir, pid, skew_s)


if __name__ == "__main__":
    main()
