"""Independent pandas oracle for ALL 22 NDS-H (TPC-H) queries.

Closes the VERDICT r4 weak #4 hole: the NDS-H leg carried the headline
perf number but was validated only engine-vs-engine (cpu_exec and
device_exec share the lexer/parser/planner, so a planner bug produces
identical wrong answers on both sides). Each query here is re-derived by
hand with pandas directly from the generated arrays — bypassing parser,
planner, and both executors. Reference stance: the reference validates
GPU Spark against CPU Spark (`nds-h/nds_h_validate.py:46-110`); this is
the stronger fully-independent version.

Conventions (match tests/test_cpu_oracle.py): money decimals are scaled
int64 (divide by 100), dates are epoch days via tpch.days(); TPC-H data
carries no NULLs. Parameters are the spec §2.4 qualification values
(the streams module's render_query defaults).
"""

import numpy as np
import pandas as pd
import pytest

from nds_tpu.datagen import tpch
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h import streams
from nds_tpu.nds_h.schema import get_schemas

SF = 0.01


@pytest.fixture(scope="module")
def raw():
    return {t: tpch.gen_table(t, SF) for t in get_schemas()}


@pytest.fixture(scope="module")
def F(raw):
    cache = {}

    def get(t: str) -> pd.DataFrame:
        if t not in cache:
            cache[t] = pd.DataFrame(dict(raw[t]))
        return cache[t].copy()

    return get


@pytest.fixture(scope="module")
def session(raw):
    schemas = get_schemas()
    sess = Session.for_nds_h()
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


def run(session, qn: int):
    result = None
    for s in streams.statements(qn):
        r = session.sql(s)
        if r is not None:
            result = r
    return result.to_pandas()


def _plus_months(iso: str, n: int) -> int:
    m = np.datetime64(iso[:7], "M") + n
    return int(np.datetime64(str(m) + "-" + iso[8:], "D").astype(int))


def _rev(df) -> pd.Series:
    return df.l_extendedprice / 100 * (1 - df.l_discount / 100)


def test_q1_pricing_summary(session, F):
    li = F("lineitem")
    d = li[li.l_shipdate <= tpch.days("1998-12-01") - 90]
    got = run(session, 1)
    exp = d.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", lambda s: s.sum() / 100),
        count_order=("l_quantity", "size")).reset_index()
    assert list(got.iloc[:, 0]) == list(exp.l_returnflag)
    np.testing.assert_allclose(got["sum_qty"].astype(float),
                               exp.sum_qty, rtol=1e-9)
    disc_price = (_rev(d).groupby(
        [d.l_returnflag, d.l_linestatus]).sum().reset_index(drop=True))
    np.testing.assert_allclose(got["sum_disc_price"].astype(float),
                               disc_price, rtol=1e-9)
    assert list(got["count_order"]) == list(exp.count_order)


def test_q2_min_cost_supplier(session, F):
    p, s, ps, n, r = (F(t) for t in
                      ("part", "supplier", "partsupp", "nation", "region"))
    eu = ps.merge(s, left_on="ps_suppkey", right_on="s_suppkey") \
           .merge(n, left_on="s_nationkey", right_on="n_nationkey") \
           .merge(r[r.r_name == "EUROPE"], left_on="n_regionkey",
                  right_on="r_regionkey")
    minc = eu.groupby("ps_partkey")["ps_supplycost"].min()
    sel = p[(p.p_size == 15) & p.p_type.str.endswith("BRASS")]
    m = eu.merge(sel, left_on="ps_partkey", right_on="p_partkey")
    m = m[m.ps_supplycost == m.ps_partkey.map(minc)]
    m = m.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                      ascending=[False, True, True, True]).head(100)
    got = run(session, 2)
    assert list(got["p_partkey"]) == list(m.p_partkey)
    assert list(got["s_name"]) == list(m.s_name)
    np.testing.assert_allclose(got["s_acctbal"].astype(float),
                               m.s_acctbal / 100, rtol=1e-9)


def test_q3_shipping_priority(session, F):
    c, o, li = F("customer"), F("orders"), F("lineitem")
    date = tpch.days("1995-03-15")
    m = li[li.l_shipdate > date] \
        .merge(o[o.o_orderdate < date], left_on="l_orderkey",
               right_on="o_orderkey") \
        .merge(c[c.c_mktsegment == "BUILDING"], left_on="o_custkey",
               right_on="c_custkey")
    m["rev"] = _rev(m)
    g = m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                  as_index=False)["rev"].sum()
    g = g.sort_values(["rev", "o_orderdate"],
                      ascending=[False, True]).head(10)
    got = run(session, 3)
    assert list(got["l_orderkey"]) == list(g.l_orderkey)
    np.testing.assert_allclose(got["revenue"].astype(float), g.rev,
                               rtol=1e-9)


def test_q4_order_priority(session, F):
    o, li = F("orders"), F("lineitem")
    lo, hi = tpch.days("1993-07-01"), _plus_months("1993-07-01", 3)
    late = set(li[li.l_commitdate < li.l_receiptdate].l_orderkey)
    sel = o[(o.o_orderdate >= lo) & (o.o_orderdate < hi)
            & o.o_orderkey.isin(late)]
    exp = sel.groupby("o_orderpriority").size().sort_index()
    got = run(session, 4)
    assert list(got.iloc[:, 0]) == list(exp.index)
    assert list(got["order_count"]) == list(exp)


def test_q5_local_supplier_volume(session, F):
    c, o, li, s, n, r = (F(t) for t in (
        "customer", "orders", "lineitem", "supplier", "nation", "region"))
    lo, hi = tpch.days("1994-01-01"), tpch.days("1995-01-01")
    m = li.merge(o[(o.o_orderdate >= lo) & (o.o_orderdate < hi)],
                 left_on="l_orderkey", right_on="o_orderkey") \
          .merge(c, left_on="o_custkey", right_on="c_custkey") \
          .merge(s, left_on="l_suppkey", right_on="s_suppkey")
    m = m[m.c_nationkey == m.s_nationkey]
    m = m.merge(n, left_on="s_nationkey", right_on="n_nationkey") \
         .merge(r[r.r_name == "ASIA"], left_on="n_regionkey",
                right_on="r_regionkey")
    m["rev"] = _rev(m)
    exp = m.groupby("n_name")["rev"].sum().sort_values(ascending=False)
    got = run(session, 5)
    assert list(got["n_name"]) == list(exp.index)
    np.testing.assert_allclose(got["revenue"].astype(float), exp,
                               rtol=1e-9)


def test_q6_forecast_revenue(session, F):
    li = F("lineitem")
    lo, hi = tpch.days("1994-01-01"), tpch.days("1995-01-01")
    m = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)
           & (li.l_discount >= 5) & (li.l_discount <= 7)
           & (li.l_quantity < 2400)]
    exp = (m.l_extendedprice / 100 * m.l_discount / 100).sum()
    got = run(session, 6)
    assert float(got.iloc[0, 0]) == pytest.approx(exp, rel=1e-9)


def test_q7_volume_shipping(session, F):
    s, li, o, c, n = (F(t) for t in (
        "supplier", "lineitem", "orders", "customer", "nation"))
    lo, hi = tpch.days("1995-01-01"), tpch.days("1996-12-31")
    m = li[(li.l_shipdate >= lo) & (li.l_shipdate <= hi)] \
        .merge(s, left_on="l_suppkey", right_on="s_suppkey") \
        .merge(o, left_on="l_orderkey", right_on="o_orderkey") \
        .merge(c, left_on="o_custkey", right_on="c_custkey")
    nm = dict(zip(n.n_nationkey, n.n_name))
    m["supp_nation"] = m.s_nationkey.map(nm)
    m["cust_nation"] = m.c_nationkey.map(nm)
    m = m[((m.supp_nation == "FRANCE") & (m.cust_nation == "GERMANY"))
          | ((m.supp_nation == "GERMANY") & (m.cust_nation == "FRANCE"))]
    m["l_year"] = (m.l_shipdate.to_numpy().astype("datetime64[D]")
                   .astype("datetime64[Y]").astype(int) + 1970)
    m["vol"] = _rev(m)
    exp = m.groupby(["supp_nation", "cust_nation", "l_year"])[
        "vol"].sum().reset_index()
    got = run(session, 7)
    assert len(got) == len(exp)
    if len(exp):
        assert list(got["supp_nation"]) == list(exp.supp_nation)
        assert list(got["l_year"].astype(int)) == list(exp.l_year)
        np.testing.assert_allclose(got["revenue"].astype(float),
                                   exp.vol, rtol=1e-9)


def test_q8_market_share(session, F):
    p, s, li, o, c, n, r = (F(t) for t in (
        "part", "supplier", "lineitem", "orders", "customer", "nation",
        "region"))
    lo, hi = tpch.days("1995-01-01"), tpch.days("1996-12-31")
    m = li.merge(p[p.p_type == "ECONOMY ANODIZED STEEL"],
                 left_on="l_partkey", right_on="p_partkey") \
          .merge(s, left_on="l_suppkey", right_on="s_suppkey") \
          .merge(o[(o.o_orderdate >= lo) & (o.o_orderdate <= hi)],
                 left_on="l_orderkey", right_on="o_orderkey") \
          .merge(c, left_on="o_custkey", right_on="c_custkey") \
          .merge(n.add_prefix("c1_"), left_on="c_nationkey",
                 right_on="c1_n_nationkey") \
          .merge(r[r.r_name == "AMERICA"], left_on="c1_n_regionkey",
                 right_on="r_regionkey")
    nm = dict(zip(n.n_nationkey, n.n_name))
    m["nation"] = m.s_nationkey.map(nm)
    m["o_year"] = (m.o_orderdate.to_numpy().astype("datetime64[D]")
                   .astype("datetime64[Y]").astype(int) + 1970)
    m["vol"] = _rev(m)
    g = m.groupby("o_year").apply(
        lambda d: d[d.nation == "BRAZIL"].vol.sum() / d.vol.sum(),
        include_groups=False)
    got = run(session, 8)
    assert len(got) == len(g)
    if len(g):
        assert list(got["o_year"].astype(int)) == list(g.index)
        np.testing.assert_allclose(got["mkt_share"].astype(float), g,
                                   rtol=1e-9)


def test_q9_product_profit(session, F):
    p, s, li, ps, o, n = (F(t) for t in (
        "part", "supplier", "lineitem", "partsupp", "orders", "nation"))
    m = li.merge(p[p.p_name.str.contains("green")], left_on="l_partkey",
                 right_on="p_partkey") \
          .merge(s, left_on="l_suppkey", right_on="s_suppkey") \
          .merge(ps, left_on=["l_partkey", "l_suppkey"],
                 right_on=["ps_partkey", "ps_suppkey"]) \
          .merge(o, left_on="l_orderkey", right_on="o_orderkey") \
          .merge(n, left_on="s_nationkey", right_on="n_nationkey")
    m["o_year"] = (m.o_orderdate.to_numpy().astype("datetime64[D]")
                   .astype("datetime64[Y]").astype(int) + 1970)
    m["amount"] = (_rev(m)
                   - m.ps_supplycost / 100 * m.l_quantity / 100)
    exp = m.groupby(["n_name", "o_year"])["amount"].sum().reset_index() \
           .sort_values(["n_name", "o_year"], ascending=[True, False])
    got = run(session, 9)
    assert list(got["nation"]) == list(exp.n_name)
    assert list(got["o_year"].astype(int)) == list(exp.o_year)
    np.testing.assert_allclose(got["sum_profit"].astype(float),
                               exp.amount, rtol=1e-9)


def test_q10_returned_items(session, F):
    c, o, li, n = (F(t) for t in
                   ("customer", "orders", "lineitem", "nation"))
    lo, hi = tpch.days("1993-10-01"), _plus_months("1993-10-01", 3)
    m = li[li.l_returnflag == "R"] \
        .merge(o[(o.o_orderdate >= lo) & (o.o_orderdate < hi)],
               left_on="l_orderkey", right_on="o_orderkey") \
        .merge(c, left_on="o_custkey", right_on="c_custkey") \
        .merge(n, left_on="c_nationkey", right_on="n_nationkey")
    m["rev"] = _rev(m)
    g = m.groupby(["c_custkey", "c_name"], as_index=False)["rev"].sum()
    g = g.sort_values("rev", ascending=False).head(20)
    got = run(session, 10)
    assert list(got["c_custkey"]) == list(g.c_custkey)
    np.testing.assert_allclose(got["revenue"].astype(float), g.rev,
                               rtol=1e-9)


def test_q11_important_stock(session, F):
    ps, s, n = F("partsupp"), F("supplier"), F("nation")
    de = ps.merge(s, left_on="ps_suppkey", right_on="s_suppkey") \
           .merge(n[n.n_name == "GERMANY"], left_on="s_nationkey",
                  right_on="n_nationkey")
    de["val"] = de.ps_supplycost / 100 * de.ps_availqty
    thresh = de.val.sum() * 0.0001
    g = de.groupby("ps_partkey")["val"].sum()
    g = g[g > thresh].sort_values(ascending=False)
    got = run(session, 11)
    assert list(got["ps_partkey"]) == list(g.index)
    np.testing.assert_allclose(got.iloc[:, 1].astype(float), g,
                               rtol=1e-9)


def test_q12_shipmode_priority(session, F):
    o, li = F("orders"), F("lineitem")
    lo, hi = tpch.days("1994-01-01"), tpch.days("1995-01-01")
    m = li[li.l_shipmode.isin(["MAIL", "SHIP"])
           & (li.l_commitdate < li.l_receiptdate)
           & (li.l_shipdate < li.l_commitdate)
           & (li.l_receiptdate >= lo) & (li.l_receiptdate < hi)] \
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
    exp = m.groupby("l_shipmode").apply(
        lambda d: (d.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).sum(),
                   (~d.o_orderpriority.isin(["1-URGENT", "2-HIGH"])).sum()),
        include_groups=False).sort_index()
    got = run(session, 12)
    assert list(got["l_shipmode"]) == list(exp.index)
    assert [(int(a), int(b)) for a, b in
            zip(got["high_line_count"], got["low_line_count"])] \
        == [(int(a), int(b)) for a, b in exp]


def test_q14_promo_effect(session, F):
    li, p = F("lineitem"), F("part")
    lo, hi = tpch.days("1995-09-01"), _plus_months("1995-09-01", 1)
    m = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)] \
        .merge(p, left_on="l_partkey", right_on="p_partkey")
    m["rev"] = _rev(m)
    exp = 100.0 * m[m.p_type.str.startswith("PROMO")].rev.sum() \
        / m.rev.sum()
    got = run(session, 14)
    assert float(got.iloc[0, 0]) == pytest.approx(exp, rel=1e-9)


def test_q15_top_supplier_view(session, F):
    li, s = F("lineitem"), F("supplier")
    lo, hi = tpch.days("1996-01-01"), _plus_months("1996-01-01", 3)
    d = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)].copy()
    d["rev"] = _rev(d)
    g = d.groupby("l_suppkey")["rev"].sum()
    top = g[g == g.max()]
    m = s[s.s_suppkey.isin(top.index)].sort_values("s_suppkey")
    got = run(session, 15)
    assert list(got["s_suppkey"]) == list(m.s_suppkey)
    np.testing.assert_allclose(
        got["total_revenue"].astype(float),
        [g[k] for k in m.s_suppkey], rtol=1e-9)


def test_q16_parts_supplier_cnt(session, F):
    ps, p, s = F("partsupp"), F("part"), F("supplier")
    bad = set(s[s.s_comment.str.contains("Customer.*Complaints",
                                         regex=True)].s_suppkey)
    sel = p[(p.p_brand != "Brand#45")
            & ~p.p_type.str.startswith("MEDIUM POLISHED")
            & p.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    m = ps[~ps.ps_suppkey.isin(bad)].merge(
        sel, left_on="ps_partkey", right_on="p_partkey")
    exp = m.groupby(["p_brand", "p_type", "p_size"])[
        "ps_suppkey"].nunique().reset_index(name="cnt")
    exp = exp.sort_values(["cnt", "p_brand", "p_type", "p_size"],
                          ascending=[False, True, True, True])
    got = run(session, 16)
    assert list(got["supplier_cnt"]) == list(exp.cnt)
    assert list(got["p_brand"]) == list(exp.p_brand)
    assert list(got["p_size"].astype(int)) == list(exp.p_size)


def test_q17_small_quantity(session, F):
    li, p = F("lineitem"), F("part")
    sel = p[(p.p_brand == "Brand#23") & (p.p_container == "MED BOX")]
    m = li.merge(sel, left_on="l_partkey", right_on="p_partkey")
    avg02 = li.groupby("l_partkey")["l_quantity"].mean() * 0.2
    m = m[m.l_quantity < m.l_partkey.map(avg02)]
    exp = m.l_extendedprice.sum() / 100 / 7.0 if len(m) else None
    got = run(session, 17)
    v = got.iloc[0, 0]
    if exp is None:
        assert v is None or pd.isna(v)
    else:
        assert float(v) == pytest.approx(exp, rel=1e-9)


def test_q18_large_volume(session, F):
    li, o, c = F("lineitem"), F("orders"), F("customer")
    qty = li.groupby("l_orderkey")["l_quantity"].sum()
    big = qty[qty > 30000].index
    m = o[o.o_orderkey.isin(big)] \
        .merge(c, left_on="o_custkey", right_on="c_custkey")
    m = m.sort_values(["o_totalprice", "o_orderdate"],
                      ascending=[False, True]).head(100)
    got = run(session, 18)
    assert list(got["o_orderkey"]) == list(m.o_orderkey)
    np.testing.assert_allclose(
        got.iloc[:, 5].astype(float),
        [qty[k] / 100 for k in m.o_orderkey], rtol=1e-9)


def test_q19_discounted_revenue(session, F):
    li, p = F("lineitem"), F("part")
    m = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    base = m.l_shipmode.isin(["AIR", "AIR REG"]) \
        & (m.l_shipinstruct == "DELIVER IN PERSON")
    b1 = (base & (m.p_brand == "Brand#12")
          & m.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
          & (m.l_quantity >= 100) & (m.l_quantity <= 1100)
          & (m.p_size >= 1) & (m.p_size <= 5))
    b2 = (base & (m.p_brand == "Brand#23")
          & m.p_container.isin(["MED BAG", "MED BOX", "MED PKG",
                                "MED PACK"])
          & (m.l_quantity >= 1000) & (m.l_quantity <= 2000)
          & (m.p_size >= 1) & (m.p_size <= 10))
    b3 = (base & (m.p_brand == "Brand#34")
          & m.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
          & (m.l_quantity >= 2000) & (m.l_quantity <= 3000)
          & (m.p_size >= 1) & (m.p_size <= 15))
    sel = m[b1 | b2 | b3]
    exp = _rev(sel).sum() if len(sel) else None
    got = run(session, 19)
    v = got.iloc[0, 0]
    if exp is None:
        assert v is None or pd.isna(v)
    else:
        assert float(v) == pytest.approx(exp, rel=1e-9)


def test_q20_potential_promotion(session, F):
    s, n, ps, p, li = (F(t) for t in
                       ("supplier", "nation", "partsupp", "part",
                        "lineitem"))
    lo, hi = tpch.days("1994-01-01"), tpch.days("1995-01-01")
    parts = set(p[p.p_name.str.startswith("forest")].p_partkey)
    d = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)]
    half = d.groupby(["l_partkey", "l_suppkey"])["l_quantity"].sum() \
        .mul(0.5 / 100)
    px = ps[ps.ps_partkey.isin(parts)].copy()
    key = list(zip(px.ps_partkey, px.ps_suppkey))
    px["thresh"] = [half.get(k, np.nan) for k in key]
    good = set(px[px.ps_availqty > px.thresh].ps_suppkey)
    m = s[s.s_suppkey.isin(good)] \
        .merge(n[n.n_name == "CANADA"], left_on="s_nationkey",
               right_on="n_nationkey").sort_values("s_name")
    got = run(session, 20)
    assert list(got["s_name"]) == list(m.s_name)
    assert list(got["s_address"]) == list(m.s_address)


def test_q21_suppliers_who_kept_waiting(session, F):
    s, li, o, n = (F(t) for t in
                   ("supplier", "lineitem", "orders", "nation"))
    nk = n[n.n_name == "SAUDI ARABIA"].n_nationkey.iloc[0]
    late = li[li.l_receiptdate > li.l_commitdate]
    m = late.merge(o[o.o_orderstatus == "F"], left_on="l_orderkey",
                   right_on="o_orderkey") \
            .merge(s[s.s_nationkey == nk], left_on="l_suppkey",
                   right_on="s_suppkey")
    n_supp = li.groupby("l_orderkey")["l_suppkey"].nunique()
    late_supp = late.groupby("l_orderkey")["l_suppkey"].nunique()
    m = m[(m.l_orderkey.map(n_supp) > 1)
          & (m.l_orderkey.map(late_supp).fillna(0) == 1)]
    exp = m.groupby("s_name").size().reset_index(name="numwait") \
           .sort_values(["numwait", "s_name"],
                        ascending=[False, True]).head(100)
    got = run(session, 21)
    assert list(got["s_name"]) == list(exp.s_name)
    assert list(got["numwait"]) == list(exp.numwait)


def test_q22_global_sales_opportunity(session, F):
    c, o = F("customer"), F("orders")
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cc = c[c.c_phone.str[:2].isin(codes)]
    avg = cc[cc.c_acctbal > 0].c_acctbal.mean()
    sel = cc[(cc.c_acctbal > avg) & ~cc.c_custkey.isin(o.o_custkey)]
    exp = sel.groupby(sel.c_phone.str[:2]).agg(
        numcust=("c_custkey", "size"),
        tot=("c_acctbal", lambda x: x.sum() / 100)).sort_index()
    got = run(session, 22)
    assert list(got["cntrycode"]) == list(exp.index)
    assert list(got["numcust"]) == list(exp.numcust)
    np.testing.assert_allclose(got["totacctbal"].astype(float),
                               exp.tot, rtol=1e-9)


def test_q13_customer_distribution(session, F):
    c, o = F("customer"), F("orders")
    oo = o[~o.o_comment.str.contains("special.*requests", regex=True)]
    cnt = oo.groupby("o_custkey").size()
    c_count = c.c_custkey.map(cnt).fillna(0).astype(int)
    exp = c_count.value_counts().sort_index()
    got = run(session, 13)
    assert dict(zip(got["c_count"], got["custdist"])) \
        == {int(k): int(v) for k, v in exp.items()}
