"""Cost-ledger + telemetry tests (nds_tpu/obs/costs.py, telemetry.py;
tools/ndsreport.py bank): cost extraction/normalization off fake
compiled objects, per-dispatch ledger fold semantics (sums vs maxima),
the ops_est cross-check corridor, platform-peaks precedence
(calibrated file over datasheet builtins, longest-prefix match), the
roofline predicted-time model, sampler lifecycle (start/stop
idempotence, graceful no-op on stats-less backends, bounded ring,
drain-once counter export, locksan-clean under a thread hammer), the
COST-DRIFT gate in ndsreport diff, and bank's provenance record +
stale refusal."""

import json
import os
import shutil
import sys
import threading
import time

import pytest

from nds_tpu.analysis import locksan
from nds_tpu.obs import costs, telemetry
from nds_tpu.obs.telemetry import TelemetrySampler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUN_COST = os.path.join(REPO, "tests", "fixtures", "run_cost")

sys.path.insert(0, os.path.join(REPO, "tools"))


# ----------------------------------------------------------- extraction

class FakeMemStats:
    temp_size_in_bytes = 4096
    argument_size_in_bytes = 1024
    output_size_in_bytes = 512


class FakeCompiled:
    """Duck-typed jax.stages.Compiled: list-of-dict cost_analysis (the
    older-jax shape) + attribute-style memory_analysis."""

    def __init__(self, flops=1e6, fail=False):
        self._flops = flops
        self._fail = fail

    def cost_analysis(self):
        if self._fail:
            raise NotImplementedError("backend without analysis")
        return [{"flops": self._flops, "bytes accessed": 2048.0,
                 "transcendentals": 16.0, "utilization0{}": 3.0,
                 "negative sentinel": -1.0}]

    def memory_analysis(self):
        if self._fail:
            raise NotImplementedError("backend without analysis")
        return FakeMemStats()


def test_compute_cost_normalizes_keys():
    c = costs.compute_cost(FakeCompiled())
    assert c == {"flops": 1e6, "bytes_accessed": 2048.0,
                 "transcendentals": 16.0, "temp_bytes": 4096,
                 "argument_bytes": 1024, "output_bytes": 512}


def test_compute_cost_none_when_backend_lacks_analyses():
    assert costs.compute_cost(FakeCompiled(fail=True)) is None


def test_extract_memoizes_via_attach():
    fc = FakeCompiled(flops=7.0)
    first = costs.extract(fc)
    assert first["flops"] == 7.0
    fc._flops = 999.0  # a recompute would see this
    assert costs.extract(fc)["flops"] == 7.0  # memo wins
    # a store-served dict (cache/aot.load_cached) also pins
    other = FakeCompiled()
    costs.attach(other, {"flops": 3.0})
    assert costs.extract(other) == {"flops": 3.0}


# --------------------------------------------------------------- ledger

def test_ledger_sums_dispatches_and_maxes_memory():
    led = costs.CostLedger()
    led.record("chunkscan", {"flops": 10.0, "bytes_accessed": 100.0,
                             "temp_bytes": 50})
    led.record("chunkscan", {"flops": 10.0, "bytes_accessed": 100.0,
                             "temp_bytes": 80})
    led.record("DeviceExecutor", {"flops": 5.0, "temp_bytes": 30,
                                  "output_bytes": 7})
    b = led.query_block()
    assert b["flops"] == 25.0
    assert b["bytes_accessed"] == 200.0
    assert b["transcendentals"] == 0.0
    assert b["temp_bytes"] == 80          # max, not sum
    assert b["output_bytes"] == 7
    assert b["programs"] == {"chunkscan": 2, "DeviceExecutor": 1}
    led.reset_query()
    assert led.query_block() is None


def test_ledger_disabled_records_nothing():
    from nds_tpu.utils.config import EngineConfig
    costs.LEDGER.reset_query()
    try:
        costs.configure_from(EngineConfig(
            overrides={"obs.costs.enabled": "off"}))
        assert not costs.enabled()
        costs.record_program("DeviceExecutor", FakeCompiled())
        assert costs.query_block() is None
    finally:
        costs.configure_from(None)
    assert costs.enabled()
    costs.record_program("DeviceExecutor", FakeCompiled())
    assert costs.query_block()["programs"] == {"DeviceExecutor": 1}
    costs.LEDGER.reset_query()


def test_ledger_counts_costless_dispatches():
    led = costs.CostLedger()
    led.record("DeviceExecutor", None)  # backend without analyses
    b = led.query_block()
    assert b["programs"] == {"DeviceExecutor": 1}
    assert b["flops"] == 0.0


# ---------------------------------------------------------- cross-check

def test_cross_check_in_corridor_and_drift():
    ok = costs.cross_check({"flops": 1e6, "programs": {"x": 1}}, 1e4)
    assert ok["ops_est"] == 1e4
    assert ok["flops_per_op"] == 100.0
    assert "ops_est_drift" not in ok
    hi = costs.cross_check({"flops": 1e9, "programs": {"x": 1}}, 10.0)
    assert hi["ops_est_drift"] is True
    lo = costs.cross_check({"flops": 1.0, "programs": {"x": 1}}, 1e6)
    assert lo["ops_est_drift"] is True
    assert costs.cross_check(None, 1e4) is None
    # absent/zero ops_est: no cross-check keys, never a drift flag
    plain = costs.cross_check({"flops": 1e6, "programs": {"x": 1}},
                              None)
    assert "ops_est" not in plain and "ops_est_drift" not in plain


# ------------------------------------------------------- platform peaks

def test_platform_peaks_calibrated_overrides_builtin(tmp_path,
                                                     monkeypatch):
    p = tmp_path / "peaks.json"
    p.write_text(json.dumps({"CPU": {"flops": 9e10, "mem_gbps": 12.0}}))
    monkeypatch.setenv(costs.PEAKS_ENV, str(p))
    peaks = costs.platform_peaks("cpu")
    assert peaks == {"flops": 9e10, "mem_gbps": 12.0}  # file, not 5e10
    assert costs.calibrated_mem_gbps("cpu") == 12.0
    # rewrite -> mtime cache must pick up the new numbers
    time.sleep(0.01)
    p.write_text(json.dumps({"cpu": {"flops": 1e11, "mem_gbps": 30.0}}))
    os.utime(p)
    assert costs.platform_peaks("cpu")["mem_gbps"] == 30.0


def test_platform_peaks_builtin_fallback_and_prefix(monkeypatch,
                                                    tmp_path):
    monkeypatch.setenv(costs.PEAKS_ENV,
                       str(tmp_path / "absent.json"))
    assert costs.platform_peaks("cpu") == {"flops": 5e10,
                                           "mem_gbps": 25.0}
    # longest prefix wins: a v5 lite chip must not read the v5p row
    lite = costs.platform_peaks("TPU v5 lite")
    assert lite["flops"] == 197e12
    full = costs.platform_peaks("tpu v5p")
    assert full["flops"] == 459e12
    assert costs.platform_peaks("quantum abacus") is None
    assert costs.platform_peaks(None) is None
    assert costs.calibrated_mem_gbps("cpu") is None


def test_predicted_ms_roofline(monkeypatch, tmp_path):
    monkeypatch.setenv(costs.PEAKS_ENV, str(tmp_path / "absent.json"))
    # cpu peaks: 5e10 flops, 25 GB/s -> flops-bound here
    blk = {"platform": "cpu", "flops": 5e9, "bytes_accessed": 25e6}
    assert costs.predicted_ms(blk) == pytest.approx(100.0)
    # bytes-bound: 25e9 bytes / 25 GB/s = 1 s
    blk = {"platform": "cpu", "flops": 1.0, "bytes_accessed": 25e9}
    assert costs.predicted_ms(blk) == pytest.approx(1000.0)
    assert costs.predicted_ms({"flops": 1e9}) is None  # no platform
    assert costs.predicted_ms(None) is None


# ---------------------------------------------------- sampler lifecycle

def test_sampler_lifecycle_idempotent():
    vals = iter(range(1000))
    s = TelemetrySampler(interval_ms=5, capacity=64,
                         read_fn=lambda: next(vals))
    assert not s.running()
    s.start()
    s.start()  # second start: no second thread
    assert s.running()
    time.sleep(0.06)
    s.stop()
    s.stop()  # second stop: no-op
    assert not s.running()
    b = s.query_block()
    assert b["samples"] >= 2
    assert b["interval_ms"] == 5.0
    hbm = b["hbm"]
    assert hbm["min_bytes"] <= hbm["mean_bytes"] <= hbm["max_bytes"]
    assert hbm["series"][0][0] == 0.0  # offsets start at the window


def test_sampler_noop_backend_keeps_shapes_absent():
    s = TelemetrySampler(interval_ms=5, read_fn=lambda: None)
    s.start()
    time.sleep(0.03)
    s.stop()
    assert s.query_block() is None
    assert s.snapshot_block() is None
    assert s.drain_counter_events() == []


def test_sampler_ring_is_bounded_and_series_decimated():
    s = TelemetrySampler(interval_ms=1, capacity=8,
                         read_fn=lambda: 42)
    for _ in range(50):
        s.sample()
    assert len(s._ring) == 8
    big = TelemetrySampler(interval_ms=1, capacity=4096,
                           read_fn=lambda: 1)
    for _ in range(500):
        big.sample()
    blk = big.query_block()
    assert blk["samples"] == 500
    assert len(blk["hbm"]["series"]) == telemetry.SERIES_MAX_POINTS


def test_sampler_drains_each_sample_once():
    s = TelemetrySampler(interval_ms=1, read_fn=lambda: 7)
    s.sample()
    s.sample()
    first = s.drain_counter_events()
    assert len(first) == 2
    assert s.drain_counter_events() == []
    s.sample()
    assert len(s.drain_counter_events()) == 1


def test_sampler_reset_query_windows_the_block():
    s = TelemetrySampler(interval_ms=1, read_fn=lambda: 9)
    s.sample()
    s.sample()
    s.reset_query()
    assert s.query_block() is None  # old samples fall out of window
    s.sample()
    assert s.query_block()["samples"] == 1


def test_sampler_locksan_clean_under_hammer():
    before = locksan.inversion_count()
    s = TelemetrySampler(interval_ms=1, capacity=32,
                         read_fn=lambda: 1)

    def hammer():
        for _ in range(50):
            s.start()
            s.sample()
            s.query_block()
            s.drain_counter_events()
            s.snapshot_block()
            s.reset_query()
            s.stop()

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s.stop()
    assert not s.running()
    assert locksan.inversion_count() == before


def test_configured_interval_env_wins(monkeypatch):
    monkeypatch.setenv(telemetry.TELEMETRY_ENV, "off")
    assert telemetry.configured_interval_ms(None) is None
    monkeypatch.setenv(telemetry.TELEMETRY_ENV, "125")
    assert telemetry.configured_interval_ms(None) == 125.0
    monkeypatch.delenv(telemetry.TELEMETRY_ENV)
    assert telemetry.configured_interval_ms(None) == float(
        telemetry.DEFAULT_INTERVAL_MS)


# ------------------------------------------------------ cost drift gate

def _rows(flops, nbytes):
    return {"query1": {"query": "query1", "status": "Completed",
                       "cost": {"flops": flops,
                                "bytes_accessed": nbytes,
                                "transcendentals": 0.0,
                                "programs": {"DeviceExecutor": 1}}}}


def test_cost_changes_flags_drift_both_directions():
    from nds_tpu.obs import analyze
    base = _rows(1e9, 1e8)
    up = analyze.cost_changes(base, _rows(2e9, 1e8), pct=25.0)
    assert up and up[0]["drifted"] is True
    down = analyze.cost_changes(base, _rows(4e8, 1e8), pct=25.0)
    assert down and down[0]["drifted"] is True
    flat = analyze.cost_changes(base, _rows(1.1e9, 1e8), pct=25.0)
    assert not any(e.get("drifted") for e in flat)


def test_cost_changes_respects_abs_floor():
    from nds_tpu.obs import analyze
    # 10x but under the 1e6-flop floor: noise-sized programs never gate
    tiny = analyze.cost_changes(_rows(100.0, 10.0),
                                _rows(1000.0, 10.0), pct=25.0)
    assert not any(e.get("drifted") for e in tiny)


def test_cost_changes_missing_side_never_fails():
    from nds_tpu.obs import analyze
    base = _rows(1e9, 1e8)
    cur = {"query1": {"query": "query1",
                      "status": "Completed"}}  # cost dropped
    out = analyze.cost_changes(base, cur, pct=25.0)
    assert out and out[0].get("missing")
    assert not any(e.get("drifted") for e in out)


def test_parse_gate_accepts_cost_pct():
    from nds_tpu.obs import analyze
    g = analyze.parse_gate("pct=5,abs_ms=10,cost_pct=40")
    assert g == {"pct": 5.0, "abs_ms": 10.0, "cost_pct": 40.0}
    assert analyze.parse_gate(None)["cost_pct"] == 25.0


def test_diff_gates_on_cost_drift_despite_identical_walls(tmp_path):
    """Compiler flops doubling on an unchanged query fails the gate
    even when wall-clock is byte-identical — the whole point of the
    COST-DRIFT lane."""
    from nds_tpu.obs import analyze
    cur_dir = tmp_path / "cur"
    shutil.copytree(RUN_COST, cur_dir)
    name = "fixture-query1-1754100000000.json"
    with open(cur_dir / name) as f:
        doc = json.load(f)
    doc["cost"]["flops"] *= 2.0
    doc["cost"]["flops_per_op"] *= 2.0
    with open(cur_dir / name, "w") as f:
        json.dump(doc, f)
    base = analyze.analyze_run(RUN_COST, with_trace=False)
    cur = analyze.analyze_run(str(cur_dir), with_trace=False)
    d = analyze.diff_runs(base, cur)
    assert not d["passed"]
    drifted = [e for e in d["cost_changes"] if e.get("drifted")]
    assert [e["query"] for e in drifted] == ["query1"]
    assert "COST-DRIFT" in analyze.format_diff(d)
    # identity: the same cost blocks pass, and a looser pct waives it
    ident = analyze.diff_runs(base, base)
    assert ident["passed"]
    loose = analyze.diff_runs(base, cur, cost_pct=150.0)
    assert loose["passed"]


def test_analyze_rows_carry_predicted_and_telemetry():
    from nds_tpu.obs import analyze
    a = analyze.analyze_run(RUN_COST, with_trace=False)
    rows = {r["query"]: r for r in a["queries"]}
    q1 = rows["query1"]
    assert q1["cost"]["flops"] == 2.4e9
    assert q1["predicted_ms"] > 0
    assert 0 < q1["achieved_frac"] < 1
    assert q1["telemetry_samples"] == 5
    assert q1["hbm_max_bytes"] == 2097152
    table = analyze.format_attribution(a)
    assert "predicted" in table and "achieved" in table
    html = analyze.render_html(a)
    assert "predicted" in html


# ------------------------------------------------------------- banking

def test_bank_record_provenance_and_cost_totals():
    import ndsreport
    record, err = ndsreport.bank_record(RUN_COST)
    assert err == ""
    assert record["metric"] == "power_total"
    assert record["value"] == pytest.approx(3.5)
    assert record["queries_completed"] == 3
    prov = record["provenance"]
    assert prov["platform"] == "tpu v4"  # the cost blocks' stamp
    assert prov["engine_version"] == "jax-0.4.36"
    assert prov["config_digest"] and prov["code_epoch"]
    totals = record["cost_totals"]
    assert totals["flops"] == pytest.approx(12.5e9)
    assert totals["queries_with_cost"] == 3


def test_bank_refuses_stale_dir_with_exit_4(tmp_path, capsys):
    import ndsreport
    run = tmp_path / "run"
    shutil.copytree(RUN_COST, run)
    name = "fixture-query2-1754100000001.json"
    with open(run / name) as f:
        doc = json.load(f)
    doc["stale_device_times"] = True
    with open(run / name, "w") as f:
        json.dump(doc, f)
    out = tmp_path / "record.json"
    rc = ndsreport.main(["bank", str(run), "--out", str(out)])
    assert rc == ndsreport.EXIT_STALE_BANK == 4
    assert "BANK REFUSED" in capsys.readouterr().out
    assert not out.exists()


def test_bank_refuses_empty_dir_with_exit_5(tmp_path):
    import ndsreport
    (tmp_path / "empty").mkdir()
    rc = ndsreport.main(["bank", str(tmp_path / "empty")])
    assert rc == ndsreport.EXIT_NO_METRIC == 5
