"""Child process for the 2-process multi-host integration test.

Each process provisions 4 virtual CPU devices and joins a 2-process
jax.distributed world (8 global devices): the DCN axis crosses a REAL
process boundary, which single-process virtual meshes cannot exercise.
Launched by tests/test_distributed.py::test_two_process_multihost.
"""

import os
import sys


def main() -> None:
    port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    # local virtual devices per process (argv[4], default 4): the
    # 4-process tier runs 4x2, the 2-process tier 2x4
    ndev = int(sys.argv[4]) if len(sys.argv) > 4 else 4
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["NDS_TPU_PLATFORM"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={ndev}").strip()
    os.environ.setdefault("JAX_ENABLE_X64", "true")
    # the power_core "distributed" backend reads the launch contract
    # from these (parallel/multihost.py)
    os.environ["NDS_TPU_COORDINATOR"] = f"localhost:{port}"
    os.environ["NDS_TPU_NUM_PROCESSES"] = str(nproc)
    os.environ["NDS_TPU_PROCESS_ID"] = str(pid)

    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from nds_tpu.parallel import multihost

    assert multihost.maybe_initialize(), "distributed init did not run"
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.local_devices()) == ndev
    assert len(jax.devices()) == ndev * nproc

    import numpy as np

    from nds_tpu.datagen import tpch
    from nds_tpu.engine.session import Session
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds_h.schema import get_schemas
    from nds_tpu.parallel.dist_exec import make_distributed_factory

    schemas = get_schemas()
    raw = {t: tpch.gen_table(t, 0.005) for t in schemas}

    def build(factory=None):
        s = Session.for_nds_h(factory)
        for t in schemas:
            s.register_table(from_arrays(t, schemas[t], raw[t]))
        return s

    cpu = build()
    mesh = multihost.global_mesh()
    dist = build(make_distributed_factory(mesh=mesh,
                                          shard_threshold=500))
    from nds_tpu.nds_h import streams
    for qn in (1, 3, 6):
        exp = cpu.sql(streams.render_query(qn)).to_pandas()
        got = None
        for stmt in streams.statements(qn):
            r = dist.sql(stmt)
            got = r if r is not None else got
        got = got.to_pandas()
        assert len(got) == len(exp), (qn, len(got), len(exp))
        for c in exp.columns:
            g, e = got[c].to_numpy(), exp[c].to_numpy()
            if g.dtype.kind == "f" or e.dtype.kind == "f":
                np.testing.assert_allclose(
                    g.astype(float), e.astype(float), rtol=1e-9)
            else:
                assert list(g) == list(e), (qn, c)
        print(f"rank {pid}: q{qn} OK ({len(got)} rows)", flush=True)
    # survivor-reduced scans across a REAL process world: reduced
    # buffers must build as replicated global jax.Arrays
    # (DistributedExecutor._reduced_to_device multiprocess branch)
    from nds_tpu.parallel.dist_exec import DistributedExecutor

    class SmallReduce(DistributedExecutor):
        REDUCE_MIN_ROWS = 1

    holder = {}

    def factory(tables):
        ex = holder.get("ex")
        if ex is None or ex.tables is not tables:
            ex = SmallReduce(tables, mesh=mesh, shard_threshold=500)
            holder["ex"] = ex
        return ex

    red = build(factory)
    exp = cpu.sql(streams.render_query(3)).to_pandas()
    got = red.sql(streams.render_query(3)).to_pandas()
    assert len(got) == len(exp), ("reduce", len(got), len(exp))
    for c in exp.columns:
        g, e = got[c].to_numpy(), exp[c].to_numpy()
        if g.dtype.kind == "f" or e.dtype.kind == "f":
            np.testing.assert_allclose(
                g.astype(float), e.astype(float), rtol=1e-9)
        else:
            assert list(g) == list(e), ("reduce-q3", c)
    # engagement proof: reduced buffers actually uploaded (global
    # replicated jax.Arrays in this 2-process world)
    n_red = sum(1 for k in holder["ex"]._buffers
                if "@" in k.split(".", 1)[0])
    assert n_red > 0, "no reduced buffer uploaded in multiprocess world"
    print(f"rank {pid}: reduced-scan q3 OK ({n_red} buffers)",
          flush=True)

    # rank-0-only recording contract
    assert multihost.is_primary() == (pid == 0)
    print(f"MULTIHOST_OK rank={pid}", flush=True)


if __name__ == "__main__":
    main()
