"""Differential tests for the CPU oracle executor.

Independent pandas reimplementations of representative queries (written
directly against the generated arrays, bypassing parser/planner/executor)
are the ground truth here; the oracle in turn is ground truth for the
device engine. This is the layered-oracle version of the reference's
CPU-vs-GPU differential strategy (SURVEY.md §4.1).
"""

import numpy as np
import pandas as pd
import pytest

from nds_tpu.datagen import tpch
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h import streams
from nds_tpu.nds_h.schema import get_schemas

SF = 0.01


@pytest.fixture(scope="module")
def raw():
    return {t: tpch.gen_table(t, SF) for t in get_schemas()}


@pytest.fixture(scope="module")
def frames(raw):
    out = {}
    for t, arrays in raw.items():
        df = pd.DataFrame({k: v for k, v in arrays.items()})
        out[t] = df
    return out


@pytest.fixture(scope="module")
def session(raw):
    schemas = get_schemas()
    sess = Session.for_nds_h()
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


def run_query(session, qn):
    result = None
    for s in streams.statements(qn):
        r = session.sql(s)
        if r is not None:
            result = r
    return result


class TestAgainstPandas:
    def test_q1(self, session, frames):
        li = frames["lineitem"]
        cutoff = tpch.days("1998-12-01") - 90
        d = li[li.l_shipdate <= cutoff].copy()
        d["qty"] = d.l_quantity / 100
        d["price"] = d.l_extendedprice / 100
        d["disc_price"] = d.price * (1 - d.l_discount / 100)
        d["charge"] = d.disc_price * (1 + d.l_tax / 100)
        exp = d.groupby(["l_returnflag", "l_linestatus"]).agg(
            sum_qty=("qty", "sum"), sum_base_price=("price", "sum"),
            sum_disc_price=("disc_price", "sum"), sum_charge=("charge", "sum"),
            avg_qty=("qty", "mean"), avg_price=("price", "mean"),
            avg_disc=("l_discount", lambda s: (s / 100).mean()),
            count_order=("qty", "size")).reset_index().sort_values(
            ["l_returnflag", "l_linestatus"]).reset_index(drop=True)
        got = run_query(session, 1).to_pandas()
        assert len(got) == len(exp)
        for col in ["sum_qty", "sum_base_price", "sum_disc_price",
                    "sum_charge", "avg_qty", "avg_price", "avg_disc"]:
            np.testing.assert_allclose(
                got[col].to_numpy(dtype=float),
                exp[col].to_numpy(dtype=float), rtol=1e-9)
        assert list(got["count_order"]) == list(exp["count_order"])
        assert list(got["l_returnflag"]) == list(exp["l_returnflag"])

    def test_q3(self, session, frames):
        c, o, li = frames["customer"], frames["orders"], frames["lineitem"]
        date = tpch.days("1995-03-15")
        cc = c[c.c_mktsegment == "BUILDING"]
        oo = o[o.o_orderdate < date]
        ll = li[li.l_shipdate > date].copy()
        m = ll.merge(oo, left_on="l_orderkey", right_on="o_orderkey")
        m = m.merge(cc, left_on="o_custkey", right_on="c_custkey")
        m["rev"] = m.l_extendedprice / 100 * (1 - m.l_discount / 100)
        g = m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                      as_index=False)["rev"].sum()
        g = g.sort_values(["rev", "o_orderdate"],
                          ascending=[False, True]).head(10)
        got = run_query(session, 3).to_pandas()
        assert len(got) == len(g)
        np.testing.assert_allclose(got["revenue"].to_numpy(dtype=float),
                                   g["rev"].to_numpy(), rtol=1e-9)
        assert list(got["l_orderkey"]) == list(g["l_orderkey"])

    def test_q6(self, session, frames):
        li = frames["lineitem"]
        lo, hi = tpch.days("1994-01-01"), tpch.days("1995-01-01")
        m = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)
               & (li.l_discount >= 5) & (li.l_discount <= 7)
               & (li.l_quantity < 2400)]
        exp = (m.l_extendedprice / 100 * m.l_discount / 100).sum()
        got = run_query(session, 6).to_pandas()["revenue"][0]
        assert got == pytest.approx(exp, rel=1e-9)

    def test_q13(self, session, frames):
        c, o = frames["customer"], frames["orders"]
        oo = o[~o.o_comment.str.contains("special.*requests", regex=True)]
        cnt = oo.groupby("o_custkey").size()
        c_count = c.c_custkey.map(cnt).fillna(0).astype(int)
        exp = c_count.value_counts().sort_index()
        got = run_query(session, 13).to_pandas()
        got_map = dict(zip(got.c_count, got.custdist))
        assert got_map == {int(k): int(v) for k, v in exp.items()}
        # ordering: custdist desc, c_count desc
        pairs = list(zip(got.custdist, got.c_count))
        assert pairs == sorted(pairs, key=lambda p: (-p[0], -p[1]))

    def test_q18(self, session, frames):
        li, o, c = frames["lineitem"], frames["orders"], frames["customer"]
        qty = li.groupby("l_orderkey")["l_quantity"].sum() / 100
        big = qty[qty > 300].index
        oo = o[o.o_orderkey.isin(big)]
        m = oo.merge(c, left_on="o_custkey", right_on="c_custkey")
        m = m.merge(li, left_on="o_orderkey", right_on="l_orderkey")
        g = m.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                       "o_totalprice"], as_index=False)["l_quantity"].sum()
        g["l_quantity"] /= 100
        g = g.sort_values(["o_totalprice", "o_orderdate"],
                          ascending=[False, True]).head(100)
        got = run_query(session, 18).to_pandas()
        assert len(got) == len(g)
        assert list(got["o_orderkey"]) == list(g["o_orderkey"])
        np.testing.assert_allclose(
            got.iloc[:, 5].to_numpy(dtype=float),
            g["l_quantity"].to_numpy(), rtol=1e-9)

    def test_q21(self, session, frames):
        s, li, o, n = (frames["supplier"], frames["lineitem"],
                       frames["orders"], frames["nation"])
        nk = n[n.n_name == "SAUDI ARABIA"].n_nationkey.iloc[0]
        ss = s[s.s_nationkey == nk]
        l1 = li[li.l_receiptdate > li.l_commitdate]
        oo = o[o.o_orderstatus == "F"]
        m = l1.merge(oo, left_on="l_orderkey", right_on="o_orderkey")
        m = m.merge(ss, left_on="l_suppkey", right_on="s_suppkey")
        # exists: another supplier in same order
        n_supp = li.groupby("l_orderkey")["l_suppkey"].nunique()
        m = m[m.l_orderkey.map(n_supp) > 1]
        # not exists: no OTHER supplier was late in same order
        late = li[li.l_receiptdate > li.l_commitdate]
        late_supp = late.groupby("l_orderkey")["l_suppkey"].nunique()
        m = m[m.l_orderkey.map(late_supp).fillna(0) == 1]
        exp = m.groupby("s_name").size().reset_index(name="numwait")
        exp = exp.sort_values(["numwait", "s_name"],
                              ascending=[False, True]).head(100)
        got = run_query(session, 21).to_pandas()
        assert list(got.s_name) == list(exp.s_name)
        assert list(got.numwait) == list(exp.numwait)

    def test_q22(self, session, frames):
        c, o = frames["customer"], frames["orders"]
        codes = ["13", "31", "23", "29", "30", "18", "17"]
        cc = c[c.c_phone.str[:2].isin(codes)]
        avg = cc[cc.c_acctbal > 0].c_acctbal.mean()
        sel = cc[(cc.c_acctbal > avg) & ~cc.c_custkey.isin(o.o_custkey)]
        exp = sel.groupby(sel.c_phone.str[:2]).agg(
            numcust=("c_custkey", "size"),
            tot=("c_acctbal", lambda x: x.sum() / 100)).sort_index()
        got = run_query(session, 22).to_pandas()
        assert list(got.cntrycode) == list(exp.index)
        assert list(got.numcust) == list(exp.numcust)
        np.testing.assert_allclose(got.totacctbal.to_numpy(dtype=float),
                                   exp.tot.to_numpy(), rtol=1e-9)


class TestAll22Execute:
    def test_all_queries_run(self, session):
        for qn in range(1, 23):
            result = run_query(session, qn)
            assert result is not None, f"q{qn} returned nothing"
            # shape sanity: column count matches template select list
            assert result.nrows >= 0
