"""Unified execution pipeline tests (nds_tpu/engine/scheduler.py):
cost-model placement, degradation-ladder ordering, sticky demotion +
promotion-after-N-clean, and the consensus vote protocol — all on bare
CPU with fake placement executors, no jax device work."""

import pytest

from nds_tpu.analysis import plan_verify
from nds_tpu.engine import scheduler
from nds_tpu.engine.scheduler import (
    CHUNKED, CPU, DEVICE, SHARDED, Consensus, CostModel,
    ExecutionPipeline, NullChannel,
)
from nds_tpu.engine.session import Session
from nds_tpu.resilience import faults
from nds_tpu.resilience.faults import InjectedOOM
from nds_tpu.utils.config import EngineConfig


def _plan(sql="select count(*) c from store_sales"):
    sess = Session.for_nds()
    return sess.plan(sql), sess.catalog


# ------------------------------------------------------------ cost model

class TestCostModel:
    def test_small_plan_stays_on_device(self):
        planned, catalog = _plan("select count(*) c from reason")
        cm = CostModel(device_budget=1 << 30)
        placement, why = cm.choose(planned, scheduler.UNIVERSES["tpu"],
                                   catalog=catalog)
        assert placement == DEVICE
        assert why.startswith("fits:")

    def test_large_plan_goes_out_of_core(self):
        # SF1 catalog stats: store_sales ~2.9M rows; a 1 MB budget is
        # exceeded by orders of magnitude
        planned, catalog = _plan()
        cm = CostModel(device_budget=1 << 20)
        placement, why = cm.choose(planned, scheduler.UNIVERSES["tpu"],
                                   catalog=catalog)
        assert placement == CHUNKED
        assert why.startswith("working-set:")

    def test_stream_bytes_threshold_routes_chunked(self):
        planned, catalog = _plan()
        cm = CostModel(device_budget=1 << 40, stream_bytes=1 << 20)
        placement, why = cm.choose(planned, scheduler.UNIVERSES["tpu"],
                                   catalog=catalog)
        assert placement == CHUNKED
        assert why.startswith("table-exceeds-stream-bytes")

    def test_hwm_history_demotes_repeat_offender(self):
        planned, catalog = _plan("select count(*) c from reason")
        cm = CostModel(device_budget=1 << 30)
        assert cm.choose(planned, scheduler.UNIVERSES["tpu"],
                         catalog=catalog, qname="q9")[0] == DEVICE
        cm.observe("q9", (1 << 30) + 1)  # blew the budget last run
        placement, why = cm.choose(planned, scheduler.UNIVERSES["tpu"],
                                   catalog=catalog, qname="q9")
        assert placement == CHUNKED
        assert why.startswith("hwm-history:")
        # other queries are unaffected
        assert cm.choose(planned, scheduler.UNIVERSES["tpu"],
                         catalog=catalog, qname="q8")[0] == DEVICE

    def test_cpu_universe_has_no_choice(self):
        planned, catalog = _plan()
        cm = CostModel(device_budget=1)
        assert cm.choose(planned, scheduler.UNIVERSES["cpu"],
                         catalog=catalog)[0] == CPU

    def test_estimates_follow_catalog_stats(self):
        from nds_tpu.sql import plan as P
        planned, catalog = _plan(
            "select ss_item_sk from store_sales")
        est = plan_verify.estimate_plan(planned, catalog=catalog)
        assert set(est.tables) == {"store_sales"}
        rows, nbytes = est.tables["store_sales"]
        assert rows == catalog.sizes["store_sales"]
        # bytes = rows x the scan's output width at device dtypes
        scan = next(n for n in P.walk_plan(planned.root)
                    if isinstance(n, P.Scan))
        width = sum(plan_verify._dtype_width(dt)
                    for _n, dt in scan.output)
        assert nbytes == rows * width
        assert est.widest_table_bytes == nbytes


# --------------------------------------------------------- fake executors

class FakeExec:
    """Scripted placement executor: raises per the schedule, then
    succeeds. Records every execute() call."""

    def __init__(self, fails=(), result="ok"):
        self.fails = list(fails)
        self.result = result
        self.calls = 0
        self.chunk_rows = 1 << 20      # chunked-placement surface
        self.stream_bytes = 1 << 40    # nothing streams by default
        self.last_timings = {"execute_ms": 1.0}
        self.last_query_span = None

    def execute(self, planned, key=None):
        self.calls += 1
        if self.fails:
            raise self.fails.pop(0)
        return self.result


def _pipe(backend="tpu", overrides=None, execs=None):
    cfg = EngineConfig(overrides={
        "engine.backend": backend,
        "engine.retry.base_delay_s": "0",
        **(overrides or {})})
    pipe = ExecutionPipeline(backend=backend, config=cfg)
    pipe({})
    for name, ex in (execs or {}).items():
        pipe._executors[name] = ex
    return pipe


def _oom():
    return InjectedOOM("device.execute", "RESOURCE_EXHAUSTED: test oom")


# ------------------------------------------------------- ladder ordering

class TestLadder:
    def test_rungs_for_each_start(self):
        pipe = _pipe("tpu")
        assert pipe.rungs_for(DEVICE) == [DEVICE, CHUNKED, CPU]
        assert pipe.rungs_for(CHUNKED) == [CHUNKED, CPU]
        assert pipe.rungs_for(CPU) == [CPU]
        dist = _pipe("distributed")
        assert dist.rungs_for(SHARDED) == [SHARDED, CHUNKED, CPU]

    def test_floor_truncates_ladder(self):
        pipe = _pipe("tpu", {"engine.placement.floor": "chunked"})
        assert pipe.rungs_for(DEVICE) == [DEVICE, CHUNKED]

    def test_fallback_alias_forces_cpu_floor(self):
        pipe = _pipe("tpu", {"engine.placement.floor": "chunked",
                             "engine.fallback": "cpu"})
        assert pipe.rungs_for(DEVICE) == [DEVICE, CHUNKED, CPU]

    def test_ladder_off_is_single_rung(self):
        pipe = _pipe("tpu", {"engine.placement.ladder": "off"})
        assert pipe.rungs_for(DEVICE) == [DEVICE]

    def test_oom_walks_full_ladder_in_order(self):
        dev, chk, cpu = (FakeExec([_oom()]), FakeExec([_oom()]),
                         FakeExec())
        pipe = _pipe(execs={DEVICE: dev, CHUNKED: chk, CPU: cpu})
        planned, _cat = _plan("select count(*) c from reason")
        assert pipe.execute(planned) == "ok"
        assert (dev.calls, chk.calls, cpu.calls) == (1, 1, 1)
        assert pipe.last_schedule["ladder"] == [DEVICE, CHUNKED, CPU]
        assert pipe.last_schedule["reschedules"] == 2
        assert pipe.last_schedule["placement"] == CPU
        assert pipe.last_stats.retries == 0  # reschedules, not retries

    def test_reschedule_halves_chunk_rows_for_that_query_only(self):
        class Recording(FakeExec):
            seen = None

            def execute(self, planned, key=None):
                Recording.seen = self.chunk_rows
                return super().execute(planned, key)

        chk = Recording()
        chk.chunk_rows = 1 << 20
        pipe = _pipe(execs={DEVICE: FakeExec([_oom()]), CHUNKED: chk})
        planned, _ = _plan("select count(*) c from reason")
        pipe.execute(planned)
        # the rescheduled query ran at HALF the configured chunk size…
        assert Recording.seen == 1 << 19
        # …and the halving rolled back afterwards: repeated walks must
        # not grind later chunked queries down to the floor
        assert chk.chunk_rows == 1 << 20

    def test_chunked_relief_lowers_stream_threshold_for_the_query(self):
        """Entering chunked as a RELIEF placement (ladder / cost-model
        working-set) must actually stream: the largest scanned table's
        bytes cap the stream threshold for that query, then the
        threshold restores."""
        from nds_tpu.datagen import tpcds
        from nds_tpu.io.host_table import from_arrays
        from nds_tpu.nds.schema import get_schemas

        table = from_arrays("reason", get_schemas()["reason"],
                            tpcds.gen_table("reason", 0.01))

        class Recording(FakeExec):
            seen = None

            def execute(self, planned, key=None):
                Recording.seen = self.stream_bytes
                return super().execute(planned, key)

        chk = Recording()
        pipe = _pipe()
        pipe({"reason": table})
        pipe._executors.update({DEVICE: FakeExec([_oom()]),
                                CHUNKED: chk})
        planned, _ = _plan("select count(*) c from reason")
        pipe.execute(planned)
        from nds_tpu.obs.memwatch import table_bytes
        assert Recording.seen == max(table_bytes(table) - 1, 1)
        assert chk.stream_bytes == 1 << 40  # restored after the walk

    def test_generic_transient_retries_same_rung(self):
        boom = faults.InjectedTransientFault("device.execute", "flaky")
        dev = FakeExec([boom])
        pipe = _pipe(execs={DEVICE: dev})
        planned, _ = _plan("select count(*) c from reason")
        assert pipe.execute(planned) == "ok"
        assert dev.calls == 2                      # retried in place
        assert pipe.last_stats.retries == 1
        assert pipe.last_schedule["reschedules"] == 0

    def test_deterministic_never_walks(self):
        err = faults.InjectedDeterministicFault("device.execute", "bug")
        dev, cpu = FakeExec([err]), FakeExec()
        pipe = _pipe(execs={DEVICE: dev, CPU: cpu})
        planned, _ = _plan("select count(*) c from reason")
        with pytest.raises(faults.InjectedDeterministicFault):
            pipe.execute(planned)
        assert cpu.calls == 0
        assert pipe.last_stats.gave_up_reason == "deterministic"

    def test_oom_at_floor_exhausts_attempts(self):
        cpu = FakeExec([_oom(), _oom(), _oom(), _oom()])
        pipe = _pipe("cpu", execs={CPU: cpu})
        planned, _ = _plan("select count(*) c from reason")
        with pytest.raises(InjectedOOM):
            pipe.execute(planned)
        assert cpu.calls == 3  # engine.retry.max_attempts default
        assert pipe.last_stats.gave_up_reason == "attempts_exhausted(3)"

    def test_sharded_overflow_replans_with_grown_slack(self):
        class FakeSharded(FakeExec):
            slack_grown = 0

            def grow_slack(self):
                self.slack_grown += 1

        from nds_tpu.engine.device_exec import DeviceExecError
        over = DeviceExecError("exchange overflow persisted")
        sh = FakeSharded([over])
        pipe = _pipe("distributed", execs={SHARDED: sh})
        planned, _ = _plan("select count(*) c from reason")
        assert pipe.execute(planned) == "ok"
        # one overflow -> re-plan at doubled slack on the SAME rung
        assert sh.slack_grown == 1 and sh.calls == 2
        assert pipe.last_schedule["ladder"] == [SHARDED,
                                                scheduler.SHARDED_REPLAN]
        assert pipe.last_schedule["placement"] == SHARDED

    def test_sharded_overflow_persisting_demotes_to_chunked(self):
        from nds_tpu.engine.device_exec import DeviceExecError

        class FakeSharded(FakeExec):
            def grow_slack(self):
                pass

        over = [DeviceExecError("exchange overflow persisted")
                for _ in range(2)]
        sh, chk = FakeSharded(over), FakeExec()
        pipe = _pipe("distributed", execs={SHARDED: sh, CHUNKED: chk})
        planned, _ = _plan("select count(*) c from reason")
        assert pipe.execute(planned) == "ok"
        assert chk.calls == 1
        assert pipe.last_schedule["placement"] == CHUNKED


# ------------------------------------------- demotion / promotion cycle

class TestPromotion:
    def _walked_pipe(self):
        pipe = _pipe(overrides={"engine.placement.demote_after": "2",
                                "engine.placement.promote_after": "2"})
        return pipe

    def _walk_once(self, pipe, planned):
        pipe._executors[DEVICE] = FakeExec([_oom()])
        pipe._executors.setdefault(CHUNKED, FakeExec())
        pipe.execute(planned)

    def test_demotes_after_streak_and_promotes_after_clean(self):
        pipe = self._walked_pipe()
        planned, _ = _plan("select count(*) c from reason")
        # two consecutive ladder-walked queries -> sticky demotion
        self._walk_once(pipe, planned)
        assert pipe._demoted_to is None
        self._walk_once(pipe, planned)
        assert pipe._demoted_to == CHUNKED
        # demoted start: no ladder walk, placement is the demoted rung
        pipe._executors[DEVICE] = FakeExec()  # healthy again
        pipe.execute(planned)
        assert pipe.last_schedule["initial"] == CHUNKED
        assert pipe.last_schedule["reason"] == "sticky-demotion"
        assert pipe._executors[DEVICE].calls == 0
        # second clean query at the demoted rung -> promotion
        pipe.execute(planned)
        assert pipe._demoted_to is None
        # the next query records the promotion and runs at the top
        pipe.execute(planned)
        assert pipe.last_schedule.get("promoted_back") is True
        assert pipe.last_schedule["initial"] == DEVICE
        assert pipe._executors[DEVICE].calls == 1

    def test_promotion_metrics(self):
        from nds_tpu.obs import metrics as obs_metrics
        before = obs_metrics.snapshot()
        self.test_demotes_after_streak_and_promotes_after_clean()
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["placement_demotions_total"] == 1
        assert d["counters"]["placement_promotions_total"] == 1
        assert d["counters"]["query_reschedules_total"] == 2


# ------------------------------------------------------------- consensus

class SimChannel:
    """Simulated multi-rank vote transport: scripted peer votes, or a
    lagging rank that never reports (gather -> None)."""

    def __init__(self, peers, world=None, lagging=False):
        self.peers = peers
        self.world = world if world is not None else len(peers) + 1
        self.lagging = lagging
        self.gathers = 0

    def gather(self, vote):
        self.gathers += 1
        if self.lagging:
            return None
        return [vote] + list(self.peers)


class TestConsensus:
    def test_unanimous_switch(self):
        c = Consensus(SimChannel([2, 2]))
        assert c.decide(2) == 2

    def test_deepest_demotion_wins(self):
        # one rank wants rung 2, others are happy at 0: everyone goes
        # to 2 — all switch together or none do
        c = Consensus(SimChannel([0, 0]))
        assert c.decide(2) == 2
        c2 = Consensus(SimChannel([2, 1]))
        assert c2.decide(0) == 2

    def test_lagging_rank_blocks_switch(self):
        ch = SimChannel([], world=3, lagging=True)
        c = Consensus(ch)
        assert c.decide(1) is None
        assert ch.gathers == 1

    def test_partial_gather_blocks_switch(self):
        # a gather that comes back short of the world size means a
        # rank is missing: no switch
        c = Consensus(SimChannel([1], world=3))
        assert c.decide(1) is None

    def test_null_channel_is_degenerate_unanimity(self):
        c = Consensus(NullChannel())
        assert c.decide(1) == 1

    def test_multi_rank_world_has_no_mid_query_ladder(self):
        # rank-local mid-query walking cannot pair its collectives:
        # on a multi-rank world the query exhausts its single rung and
        # placement moves only through the per-query boundary vote
        dev, cpu = FakeExec([_oom()] * 3), FakeExec()
        pipe = _pipe(execs={DEVICE: dev, CPU: cpu})
        pipe.consensus = Consensus(SimChannel([], world=3,
                                              lagging=True))
        planned, _ = _plan("select count(*) c from reason")
        with pytest.raises(InjectedOOM):
            pipe.execute(planned)
        assert pipe.last_schedule["reschedules"] == 0
        assert pipe.last_stats.gave_up_reason == "attempts_exhausted(3)"
        assert cpu.calls == 0
        # the lagging rank blocked the boundary switch: nobody moves
        assert pipe._demoted_to is None

    def test_multi_rank_boundary_vote_demotes_all_together(self):
        pipe = _pipe(overrides={"engine.placement.demote_after": "1"},
                     execs={DEVICE: FakeExec([_oom()] * 3),
                            CHUNKED: FakeExec()})
        pipe.consensus = Consensus(SimChannel([1]))  # peer wants rung 1
        planned, _ = _plan("select count(*) c from reason")
        with pytest.raises(InjectedOOM):
            pipe.execute(planned)
        # the failed query demoted the START through the shared vote
        assert pipe._demoted_to == CHUNKED
        pipe.execute(planned)
        assert pipe.last_schedule["placement"] == CHUNKED
        assert pipe._executors[CHUNKED].calls == 1

    def test_multi_rank_peer_vote_can_demote_a_healthy_rank(self):
        # the deepest demotion wins even when THIS rank is clean —
        # all switch together or none do
        pipe = _pipe(execs={DEVICE: FakeExec(), CHUNKED: FakeExec()})
        pipe.consensus = Consensus(SimChannel([1]))
        planned, _ = _plan("select count(*) c from reason")
        pipe.execute(planned)  # succeeds locally, peer votes rung 1
        assert pipe._demoted_to == CHUNKED

    def test_multi_rank_boundary_promotion_requires_unanimity(self):
        pipe = _pipe(overrides={"engine.placement.demote_after": "1",
                                "engine.placement.promote_after": "1"},
                     execs={DEVICE: FakeExec([_oom()] * 3),
                            CHUNKED: FakeExec()})
        pipe.consensus = Consensus(SimChannel([1]))
        planned, _ = _plan("select count(*) c from reason")
        with pytest.raises(InjectedOOM):
            pipe.execute(planned)
        assert pipe._demoted_to == CHUNKED
        # clean query at the demoted rung: self votes promote, the
        # peer still votes for the demotion -> stay demoted
        pipe.execute(planned)
        assert pipe._demoted_to == CHUNKED
        # peers agree -> promoted, recorded on the next query
        pipe.consensus = Consensus(SimChannel([0]))
        pipe.execute(planned)
        assert pipe._demoted_to is None
        pipe.execute(planned)
        assert pipe.last_schedule.get("promoted_back") is True
        assert pipe.last_schedule["placement"] == DEVICE

    def test_consensus_metric_counts_votes(self):
        from nds_tpu.obs import metrics as obs_metrics
        before = obs_metrics.snapshot()
        Consensus(NullChannel()).decide(0)
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["placement_consensus_total"] == 1


# ----------------------------------------------------- pipeline surface

class TestPipelineSurface:
    def test_reset_query_clears_stale_state(self):
        pipe = _pipe(execs={DEVICE: FakeExec()})
        planned, _ = _plan("select count(*) c from reason")
        pipe.execute(planned)
        assert pipe.last_schedule
        pipe.reset_query()
        assert pipe.last_stats.retries == 0
        assert pipe.last_schedule == {}
        assert pipe.last_timings == {}

    def test_adopts_executor_timings(self):
        dev = FakeExec()
        dev.last_timings = {"execute_ms": 42.0}
        pipe = _pipe(execs={DEVICE: dev})
        planned, _ = _plan("select count(*) c from reason")
        pipe.execute(planned)
        assert pipe.last_timings["execute_ms"] == 42.0

    def test_rebinding_tables_drops_executors(self):
        pipe = _pipe(execs={DEVICE: FakeExec()})
        pipe({"t": object()})
        assert pipe._executors == {}

    def test_invalidate_keeps_hwm_history(self):
        pipe = _pipe(execs={DEVICE: FakeExec()})
        pipe.cost_model.observe("q1", 123)
        pipe.invalidate()
        assert pipe._executors == {}
        assert pipe.cost_model.hwm_history == {"q1": 123}

    def test_forced_placement_wins(self):
        cpu = FakeExec()
        pipe = _pipe(overrides={"engine.placement.force": "cpu"},
                     execs={CPU: cpu, DEVICE: FakeExec()})
        planned, _ = _plan("select count(*) c from reason")
        pipe.execute(planned)
        assert cpu.calls == 1
        assert pipe.last_schedule["reason"] == "forced"

    def test_query_name_threads_from_faults_context(self):
        pipe = _pipe(execs={DEVICE: FakeExec([_oom()],),
                            CHUNKED: FakeExec()})
        planned, _ = _plan("select count(*) c from reason")
        with faults.context(query="query42"):
            pipe.execute(planned)
        assert pipe.last_schedule["reschedules"] == 1
        assert pipe.last_schedule["ladder"] == [DEVICE, CHUNKED]


# ------------------------------------------------------ memory governor

class TestMemoryGovernor:
    """Proactive pre-admission checks (scheduler.MemoryGovernor): the
    projection (live bytes + estimate x expansion) demotes BEFORE
    dispatch, with hysteresis and metrics."""

    class _Est:
        def __init__(self, nbytes):
            self.bytes = nbytes

    def test_projection_over_budget_demotes_and_counts(self):
        from nds_tpu.obs import metrics as obs_metrics
        gov = scheduler.MemoryGovernor(budget=1000, expansion=2.0)
        before = obs_metrics.snapshot()
        # 600 est x 2.0 expansion = 1200 projected > 1000 budget
        reason = gov.decide(self._Est(600))
        assert reason and reason.startswith("governor:")
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"][
            "governor_preemptive_demotions_total"] == 1

    def test_under_budget_admits(self):
        gov = scheduler.MemoryGovernor(budget=10_000, expansion=2.0)
        assert gov.decide(self._Est(100)) is None
        assert gov.governing is False

    def test_hysteresis_keeps_governing_until_low_watermark(self):
        gov = scheduler.MemoryGovernor(budget=1000, expansion=1.0,
                                       low_frac=0.8)
        assert gov.decide(self._Est(1100))  # over budget: governs
        assert gov.governing
        # 900 < 1000 budget but > 800 low watermark: STILL governed
        assert gov.decide(self._Est(900))
        # 700 < 800: stands down
        assert gov.decide(self._Est(700)) is None
        assert gov.governing is False
        # and 900 admits again now that it stood down
        assert gov.decide(self._Est(900)) is None

    def test_zero_estimate_never_governs(self):
        gov = scheduler.MemoryGovernor(budget=1)
        assert gov.decide(self._Est(0)) is None

    def test_pipeline_demotes_device_query_preemptively(self):
        """Live accounted bytes + the plan estimate exceed the budget:
        the query starts CHUNKED (governed: true in the schedule)
        without ever dispatching to the device executor."""
        from nds_tpu.obs import memwatch
        dev, chk = FakeExec(), FakeExec()
        pipe = _pipe(execs={DEVICE: dev, CHUNKED: chk})
        # a registered table gives the plan estimate real row counts
        pipe._tables["reason"] = type("T", (), {"nrows": 100_000})()
        planned, _ = _plan("select count(*) c from reason")
        # force the projection over budget via the accounted tracker
        memwatch.add_live(1 << 20)
        try:
            pipe.governor = scheduler.MemoryGovernor(budget=1)
            assert pipe.execute(planned) == "ok"
        finally:
            memwatch.sub_live(1 << 20)
        assert dev.calls == 0 and chk.calls == 1
        assert pipe.last_schedule["governed"] is True
        assert pipe.last_schedule["reason"].startswith("governor:")
        assert pipe.last_schedule["reschedules"] == 0

    def test_pipeline_preshrinks_chunked_query(self):
        """A query already bound for the chunked placement pre-shrinks
        chunk_rows for THAT query and restores afterwards."""
        from nds_tpu.obs import memwatch

        class Recording(FakeExec):
            seen = None

            def execute(self, planned, key=None):
                Recording.seen = self.chunk_rows
                return super().execute(planned, key)

        chk = Recording()
        chk.chunk_rows = 1 << 14
        # cost model already picks chunked (tiny stream threshold)
        pipe = _pipe(overrides={"engine.stream_bytes": "1"},
                     execs={CHUNKED: chk})
        pipe.stream_bytes = 1
        pipe.cost_model.stream_bytes = 1
        pipe._tables["store_sales"] = type("T", (),
                                           {"nrows": 1_000_000})()
        planned, _ = _plan()
        memwatch.add_live(1 << 20)
        try:
            pipe.governor = scheduler.MemoryGovernor(budget=1)
            pipe.execute(planned)
        finally:
            memwatch.sub_live(1 << 20)
        assert Recording.seen == 1 << 13       # ran at half
        assert chk.chunk_rows == 1 << 14       # restored after
        assert pipe.last_schedule["governed"] is True

    def test_governor_off_config_disables(self):
        pipe = _pipe(overrides={"engine.placement.governor": "off"})
        assert pipe.governor is None

    def test_multi_rank_world_skips_governor(self):
        """Live memory is rank-local: a multi-rank pipeline must not
        consult it (divergent placements deadlock collectives)."""
        from nds_tpu.obs import memwatch

        class TwoRanks(NullChannel):
            world = 2

            def gather(self, vote):
                return [vote, vote]

        dev = FakeExec()
        pipe = _pipe(execs={DEVICE: dev, CHUNKED: FakeExec()})
        pipe._tables["reason"] = type("T", (), {"nrows": 100_000})()
        pipe.consensus = Consensus(TwoRanks())
        pipe.governor = scheduler.MemoryGovernor(budget=1)
        planned, _ = _plan("select count(*) c from reason")
        memwatch.add_live(1 << 20)
        try:
            pipe.execute(planned)
        finally:
            memwatch.sub_live(1 << 20)
        assert dev.calls == 1                  # stayed on device
        assert "governed" not in pipe.last_schedule

    def test_cpu_universe_never_counts_phantom_demotions(self):
        """No relief rung -> the governor is not consulted: the
        counter must not report demotions that never happened."""
        from nds_tpu.obs import memwatch
        from nds_tpu.obs import metrics as obs_metrics
        cpu = FakeExec()
        pipe = _pipe("cpu", execs={CPU: cpu})
        pipe._tables["reason"] = type("T", (), {"nrows": 100_000})()
        pipe.governor = scheduler.MemoryGovernor(budget=1)
        planned, _ = _plan("select count(*) c from reason")
        memwatch.add_live(1 << 20)
        before = obs_metrics.snapshot()
        try:
            pipe.execute(planned)
        finally:
            memwatch.sub_live(1 << 20)
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert not d.get("counters", {}).get(
            "governor_preemptive_demotions_total")
        assert cpu.calls == 1
        assert pipe.governor.governing is False
