"""NDS (TPC-DS) differential tests: device engine vs CPU oracle.

Same layered-oracle strategy as the NDS-H suite (tests/test_device_engine
.py): pandas spot-checks anchor the oracle (test_cpu_oracle-style), the
oracle anchors the device engine on every implemented template.
"""

import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.slow

from nds_tpu.datagen import tpcds
from nds_tpu.engine.device_exec import make_device_factory
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds import streams
from nds_tpu.nds.schema import get_schemas

from tests.test_device_engine import assert_frames_close

SF = 0.01


@pytest.fixture(scope="module")
def raw():
    return {t: tpcds.gen_table(t, SF) for t in get_schemas()}


def _frame(d: dict) -> pd.DataFrame:
    """Raw generator dict -> pandas frame with '#null' masks applied
    (NULL FKs become NaN, like dsdgen data read with a schema)."""
    df = pd.DataFrame(
        {k: v for k, v in d.items() if not k.endswith("#null")})
    for k, m in d.items():
        if k.endswith("#null"):
            df[k[:-5]] = df[k[:-5]].where(m)
    return df


def _mk(raw, factory=None):
    schemas = get_schemas()
    sess = Session.for_nds(factory)
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


@pytest.fixture(scope="module")
def cpu_session(raw):
    return _mk(raw)


@pytest.fixture(scope="module")
def dev_session(raw):
    return _mk(raw, make_device_factory())


def test_q7_oracle_vs_pandas(raw, cpu_session):
    ss, cd, dd, it, pr = (_frame(raw[t]) for t in (
        "store_sales", "customer_demographics", "date_dim", "item",
        "promotion"))
    m = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    m = m.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    m = m.merge(pr, left_on="ss_promo_sk", right_on="p_promo_sk")
    m = m[(m.cd_gender == "M") & (m.cd_marital_status == "S")
          & (m.cd_education_status == "College")
          & ((m.p_channel_email == "N") | (m.p_channel_event == "N"))
          & (m.d_year == 2000)]
    exp = m.groupby("i_item_id").agg(
        agg1=("ss_quantity", "mean")).reset_index().sort_values(
        "i_item_id").head(100)
    got = cpu_session.sql(streams.render_query(7)).to_pandas()
    assert list(got["i_item_id"]) == list(exp["i_item_id"])
    np.testing.assert_allclose(got["agg1"].to_numpy(dtype=float),
                               exp["agg1"].to_numpy(), rtol=1e-9)


def test_q93_oracle_vs_pandas(raw, cpu_session):
    ss = _frame(raw["store_sales"])
    sr = _frame(raw["store_returns"])
    rs = _frame(raw["reason"])
    r_sk = rs[rs.r_reason_desc == "Did not fit"].r_reason_sk
    srr = sr[sr.sr_reason_sk.isin(r_sk)]
    m = ss.merge(srr, how="inner",
                 left_on=["ss_item_sk", "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_ticket_number"])
    m["act"] = (m.ss_quantity - m.sr_return_quantity) * m.ss_sales_price
    exp = m.groupby("ss_customer_sk")["act"].sum() / 100
    got = cpu_session.sql(streams.render_query(93)).to_pandas()
    got_map = dict(zip(got.ss_customer_sk, got.sumsales))
    exp_head = exp.reset_index().sort_values(
        ["act", "ss_customer_sk"]).head(100)
    for cust, val in zip(exp_head.ss_customer_sk, exp_head.act):
        assert got_map[cust] == pytest.approx(val, rel=1e-9)


def _run(session, qn: int) -> list:
    """Run a template; multi-statement templates (q14/23/24/39) execute
    part by part (reference: `nds/nds_gen_query_stream.py:91-103` runs
    parts as separate queries) — every part's result is compared."""
    sql = streams.render_query(qn)
    results = []
    for stmt in [s for s in sql.split(";") if s.strip()]:
        r = session.sql(stmt)
        if r is not None:
            results.append(r)
    return results


@pytest.mark.parametrize("qn", streams.available_templates())
def test_nds_query_matches_oracle(qn, cpu_session, dev_session):
    exps = _run(cpu_session, qn)
    gots = _run(dev_session, qn)
    assert len(exps) == len(gots)
    for part, (e, g) in enumerate(zip(exps, gots), 1):
        assert_frames_close(g.to_pandas(), e.to_pandas(),
                            f"{qn}_part{part}")
