"""Differential tests: JAX device engine vs CPU oracle on all 22 queries.

This is the engine-tier analog of the reference's CPU-vs-GPU validation
(`nds/nds_validate.py:48-114`): the CPU oracle (itself validated against
independent pandas reimplementations in test_cpu_oracle.py) is ground
truth; every query must match row-for-row with the reference's epsilon
rules for float/decimal columns. Runs on the virtual 8-device CPU backend
(conftest), exercising the exact trace the TPU sees.
"""

import numpy as np
import pandas as pd
import pytest

from nds_tpu.datagen import tpch
from nds_tpu.engine.device_exec import make_device_factory
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h import streams
from nds_tpu.nds_h.schema import get_schemas

SF = 0.01


@pytest.fixture(scope="module")
def raw():
    return {t: tpch.gen_table(t, SF) for t in get_schemas()}


def _make_session(raw, factory=None):
    schemas = get_schemas()
    sess = Session.for_nds_h(factory)
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


@pytest.fixture(scope="module")
def cpu_session(raw):
    return _make_session(raw)


@pytest.fixture(scope="module")
def dev_session(raw):
    return _make_session(raw, make_device_factory())


def run_query(session, qn):
    result = None
    for s in streams.statements(qn):
        r = session.sql(s)
        if r is not None:
            result = r
    return result


def _canon(df: pd.DataFrame) -> pd.DataFrame:
    """Canonical row order: sort by every column, floats rounded — the
    reference validator's --ignore_ordering sort (`nds_validate.py:130-131`)
    so tie order differences between engines don't fail the diff."""
    if not len(df):
        return df
    keyed = {}
    for i, c in enumerate(df.columns):
        col = df.iloc[:, i]
        if col.dtype.kind == "f":
            keyed[f"k{i}"] = col.round(4)
        else:
            keyed[f"k{i}"] = col.astype(str)
    order = pd.DataFrame(keyed).sort_values(list(keyed)).index
    return df.loc[order].reset_index(drop=True)


def assert_frames_close(got: pd.DataFrame, exp: pd.DataFrame, qn: int):
    assert got.shape == exp.shape, (
        f"q{qn}: shape {got.shape} vs oracle {exp.shape}")
    got, exp = _canon(got), _canon(exp)
    for i in range(exp.shape[1]):
        g, e = got.iloc[:, i], exp.iloc[:, i]
        name = exp.columns[i]
        if e.dtype.kind in "fc" or g.dtype.kind in "fc":
            np.testing.assert_allclose(
                pd.to_numeric(g, errors="coerce").to_numpy(dtype=float),
                pd.to_numeric(e, errors="coerce").to_numpy(dtype=float),
                rtol=1e-6, atol=1e-6,
                err_msg=f"q{qn} col {i} ({name})")
        else:
            ge = g.isna()
            ee = e.isna()
            assert list(ge) == list(ee), f"q{qn} col {i} ({name}) null mask"
            assert list(g[~ge].astype(str)) == list(e[~ee].astype(str)), (
                f"q{qn} col {i} ({name})")


@pytest.mark.parametrize("qn", range(1, 23))
def test_query_matches_oracle(qn, cpu_session, dev_session):
    exp = run_query(cpu_session, qn).to_pandas()
    got = run_query(dev_session, qn).to_pandas()
    assert_frames_close(got, exp, qn)
