"""Independent pandas oracle for NDS (TPC-DS) queries.

These tests close the shared-frontend hole (VERDICT r3 weak #2): the CPU
oracle executor interprets the SAME logical plan as the device engine, so
a parser/planner/decorrelation bug would produce identical wrong answers
on both sides of the differential tests. Here each query is re-derived
by hand with pandas directly from the generated arrays — bypassing
parser, planner, and both executors — covering every operator class:
rollup/grouping sets, window frames, intersect/except, correlated
subqueries, outer joins with NULL keys, semi/anti joins, and the
year-over-year CTE shape. Reference stance: a fully independent oracle
engine (`nds/nds_validate.py:48-114` validates GPU Spark against CPU
Spark).

Conventions (match tests/test_cpu_oracle.py): decimals are scaled int64
(divide by 100 for dollars), dates are epoch days.
"""

import numpy as np
import pandas as pd
import pytest

from nds_tpu.datagen import tpcds
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds import streams
from nds_tpu.nds.schema import get_schemas

SF = 0.01

pytestmark = pytest.mark.slow


def _epoch(iso: str) -> int:
    return int(np.datetime64(iso, "D").astype(int))


@pytest.fixture(scope="module")
def raw():
    return {t: tpcds.gen_table(t, SF) for t in get_schemas()}


@pytest.fixture(scope="module")
def F(raw):
    """Lazily-built pandas frames with '#null' masks applied (NULL FKs
    become NaN, like dsdgen data read with a schema)."""
    cache = {}

    def get(t: str) -> pd.DataFrame:
        if t not in cache:
            d = raw[t]
            df = pd.DataFrame(
                {k: v for k, v in d.items() if not k.endswith("#null")})
            for k, m in d.items():
                if k.endswith("#null"):
                    df[k[:-5]] = df[k[:-5]].where(m)
            cache[t] = df
        return cache[t].copy()

    return get


@pytest.fixture(scope="module")
def session(raw):
    schemas = get_schemas()
    sess = Session.for_nds()
    for t in schemas:
        sess.register_table(from_arrays(t, schemas[t], raw[t]))
    return sess


def run(session, qn: int) -> list[pd.DataFrame]:
    out = []
    for stmt in [s for s in streams.render_query(qn).split(";")
                 if s.strip()]:
        r = session.sql(stmt)
        if r is not None:
            out.append(r.to_pandas())
    return out


def _vals(df: pd.DataFrame, col) -> np.ndarray:
    return df[col].to_numpy(dtype=float)


# --------------------------------------------- correlated subqueries


def test_q1_correlated_avg(session, F):
    """q1: per-store correlated avg over a CTE (classic decorrelation)."""
    sr, dd, st, cu = (F(t) for t in
                      ("store_returns", "date_dim", "store", "customer"))
    m = sr.merge(dd[dd.d_year == 2000], left_on="sr_returned_date_sk",
                 right_on="d_date_sk")
    ctr = m.groupby(["sr_customer_sk", "sr_store_sk"], dropna=False).agg(
        total=("sr_return_amt", "sum")).reset_index()
    avg_per_store = ctr.groupby("sr_store_sk")["total"].mean()
    ctr["thresh"] = ctr.sr_store_sk.map(avg_per_store) * 1.2
    k = ctr[ctr.total > ctr.thresh]
    k = k.merge(st[st.s_state == "TX"], left_on="sr_store_sk",
                right_on="s_store_sk")
    k = k.merge(cu, left_on="sr_customer_sk", right_on="c_customer_sk")
    exp = sorted(k.c_customer_id)[:100]
    got = run(session, 1)[-1]
    assert list(got.iloc[:, 0]) == exp


def test_q6_scalar_and_correlated(session, F):
    """q6: scalar subquery (month_seq) + correlated per-category avg
    price + HAVING."""
    ca, cu, ss, dd, it = (F(t) for t in (
        "customer_address", "customer", "store_sales", "date_dim",
        "item"))
    mseq = dd[(dd.d_year == 2001) & (dd.d_moy == 1)].d_month_seq.unique()
    assert len(mseq) == 1
    cat_avg = it.groupby("i_category")["i_current_price"].mean()
    it["thresh"] = it.i_category.map(cat_avg) * 1.2
    hot = it[it.i_current_price > it.thresh]
    m = ss.merge(dd[dd.d_month_seq == mseq[0]],
                 left_on="ss_sold_date_sk", right_on="d_date_sk")
    m = m.merge(hot, left_on="ss_item_sk", right_on="i_item_sk")
    m = m.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
    m = m.merge(ca, left_on="c_current_addr_sk",
                right_on="ca_address_sk")
    g = m.groupby("ca_state", dropna=False).size()
    g = g[g >= 10]
    assert len(g) <= 100  # limit must not truncate for the set compare
    got = run(session, 6)[-1]
    exp = {(None if pd.isna(k) else k): int(v) for k, v in g.items()}
    gmap = {(None if pd.isna(r.iloc[0]) else r.iloc[0]): int(r.iloc[1])
            for _, r in got.iterrows()}
    assert gmap == exp


def test_q32_correlated_discount(session, F):
    """q32: correlated 1.3*avg over a date-bounded fact slice."""
    cs, it, dd = (F(t) for t in ("catalog_sales", "item", "date_dim"))
    lo, hi = _epoch("1998-03-18"), _epoch("1998-03-18") + 90
    dsel = dd[(dd.d_date >= lo) & (dd.d_date <= hi)]
    csd = cs.merge(dsel[["d_date_sk"]], left_on="cs_sold_date_sk",
                   right_on="d_date_sk")
    per_item = csd.groupby("cs_item_sk")["cs_ext_discount_amt"].mean()
    m = csd.merge(it[it.i_manufact_id == 320], left_on="cs_item_sk",
                  right_on="i_item_sk")
    m = m[m.cs_ext_discount_amt > 1.3 * m.cs_item_sk.map(per_item)]
    exp = m.cs_ext_discount_amt.sum() / 100 if len(m) else None
    got = run(session, 32)[-1]
    v = got.iloc[0, 0]
    if exp is None:
        assert v is None or pd.isna(v)
    else:
        assert float(v) == pytest.approx(exp, rel=1e-9)


# --------------------------------------------- intersect / except


def test_q8_intersect_zip_prefix(session, F):
    """q8: INTERSECT of zip lists + 2-char-prefix theta join."""
    ca, cu, ss, dd, st = (F(t) for t in (
        "customer_address", "customer", "store_sales", "date_dim",
        "store"))
    zips = ('10043', '10079', '10109', '10125', '10129', '10483',
            '11262', '13063', '13297', '14539', '17227', '18621',
            '22529', '23255', '25586', '28367', '30009', '33021',
            '36420', '39986')
    z5 = ca.ca_zip.dropna().astype(str).str[:5]
    side1 = set(z5[z5.isin(zips)])
    pref = cu[cu.c_preferred_cust_flag == "Y"]
    m = ca.merge(pref, left_on="ca_address_sk",
                 right_on="c_current_addr_sk")
    z = m.ca_zip.astype(str).str[:5]
    counts = z[m.ca_zip.notna()].groupby(z).size()
    side2 = set(counts[counts > 1].index)
    v1 = sorted(side1 & side2)
    sales = ss.merge(dd[(dd.d_qoy == 2) & (dd.d_year == 1998)],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
    sales = sales.merge(st, left_on="ss_store_sk",
                        right_on="s_store_sk")
    sales["zip2"] = sales.s_zip.astype(str).str[:2]
    v1df = pd.DataFrame({"ca_zip": pd.Series(v1, dtype=object)})
    v1df["zip2"] = v1df.ca_zip.astype(str).str[:2]
    # one output row per (sale, matching zip) — v1 is deduped by the
    # INTERSECT but distinct zips sharing a prefix still multiply
    j = sales.merge(v1df, on="zip2")
    g = j.groupby("s_store_name")["ss_net_profit"].sum() / 100
    got = run(session, 8)[-1]
    assert list(got.iloc[:, 0]) == sorted(g.index)[:100]
    gmap = dict(zip(got.iloc[:, 0], got.iloc[:, 1]))
    for name, v in g.items():
        assert float(gmap[name]) == pytest.approx(v, rel=1e-9)


def _channel_cust(F, fact, date_col, cust_col):
    dd, cu = F("date_dim"), F("customer")
    f = F(fact)
    m = f.merge(dd[(dd.d_month_seq >= 1212) & (dd.d_month_seq <= 1223)],
                left_on=date_col, right_on="d_date_sk")
    m = m.merge(cu, left_on=cust_col, right_on="c_customer_sk")
    sent = "\x00"
    return set(zip(m.c_last_name.fillna(sent), m.c_first_name.fillna(sent),
                   m.d_date))


def test_q38_intersect_three_channels(session, F):
    """q38: 3-way INTERSECT of DISTINCT name/date sets (NULLs compare
    equal in set ops)."""
    s1 = _channel_cust(F, "store_sales", "ss_sold_date_sk",
                       "ss_customer_sk")
    s2 = _channel_cust(F, "catalog_sales", "cs_sold_date_sk",
                       "cs_bill_customer_sk")
    s3 = _channel_cust(F, "web_sales", "ws_sold_date_sk",
                       "ws_bill_customer_sk")
    exp = len(s1 & s2 & s3)
    got = run(session, 38)[-1]
    assert int(got.iloc[0, 0]) == exp


# --------------------------------------------- rollup / grouping sets


def test_q22_rollup(session, F):
    """q22: 4-level ROLLUP average with NULL-padded subtotal rows."""
    inv, dd, it = (F(t) for t in ("inventory", "date_dim", "item"))
    m = inv.merge(dd[(dd.d_month_seq >= 1176) & (dd.d_month_seq <= 1187)],
                  left_on="inv_date_sk", right_on="d_date_sk")
    m = m.merge(it, left_on="inv_item_sk", right_on="i_item_sk")
    keys = ["i_product_name", "i_brand", "i_class", "i_category"]
    parts = []
    for lvl in range(5):  # rollup prefixes: all 4 keys ... empty
        ks = keys[:4 - lvl]
        if ks:
            g = m.groupby(ks, dropna=False)[
                "inv_quantity_on_hand"].mean().reset_index()
        else:
            g = pd.DataFrame(
                {"inv_quantity_on_hand": [m.inv_quantity_on_hand.mean()]})
        for k in keys:
            if k not in g.columns:
                g[k] = None
        parts.append(g[keys + ["inv_quantity_on_hand"]])
    exp = pd.concat(parts, ignore_index=True).rename(
        columns={"inv_quantity_on_hand": "qoh"})
    exp["qoh_r"] = exp.qoh.round(6)
    exp = exp.sort_values(["qoh_r"] + keys,
                          na_position="last").head(100)
    got = run(session, 22)[-1]
    assert len(got) == len(exp)
    np.testing.assert_allclose(_vals(got, got.columns[-1]),
                               exp.qoh.to_numpy(), rtol=1e-9)
    for i, k in enumerate(keys):
        g = [None if pd.isna(x) else x for x in got.iloc[:, i]]
        e = [None if pd.isna(x) else x for x in exp[k]]
        assert g == e, f"key col {k}"


def test_q36_rollup_grouping_rank(session, F):
    """q36: 2-level ROLLUP + grouping() hierarchy + rank within parent."""
    ss, dd, it, st = (F(t) for t in
                      ("store_sales", "date_dim", "item", "store"))
    m = ss.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                 right_on="d_date_sk")
    m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    m = m.merge(st[st.s_state.isin(
        ["FL", "IL", "KY", "LA", "PA", "SD"])],
        left_on="ss_store_sk", right_on="s_store_sk")
    rows = []
    base = m.groupby(["i_category", "i_class"], dropna=False).agg(
        np_=("ss_net_profit", "sum"),
        sp=("ss_ext_sales_price", "sum")).reset_index()
    for _, r in base.iterrows():
        rows.append((r.np_ / r.sp, r.i_category, r.i_class, 0))
    lvl1 = m.groupby("i_category", dropna=False).agg(
        np_=("ss_net_profit", "sum"),
        sp=("ss_ext_sales_price", "sum")).reset_index()
    for _, r in lvl1.iterrows():
        rows.append((r.np_ / r.sp, r.i_category, None, 1))
    rows.append((m.ss_net_profit.sum() / m.ss_ext_sales_price.sum(),
                 None, None, 2))
    exp = pd.DataFrame(rows, columns=["gm", "icat", "icls", "loch"])
    # rank within parent: partition (lochierarchy, cat when cls level)
    exp["pkey"] = [
        (r.loch, r.icat if r.loch == 0 and not pd.isna(r.icat) else None)
        for _, r in exp.iterrows()]
    exp["rank"] = exp.groupby("pkey")["gm"].rank(method="min")
    got = run(session, 36)[-1]
    gset = {(round(float(r.iloc[0]), 9),
             None if pd.isna(r.iloc[1]) else r.iloc[1],
             None if pd.isna(r.iloc[2]) else r.iloc[2],
             int(r.iloc[3]), int(r.iloc[4])) for _, r in got.iterrows()}
    eset = {(round(float(r.gm), 9),
             None if pd.isna(r.icat) else r.icat,
             None if pd.isna(r.icls) else r.icls,
             int(r.loch), int(r["rank"])) for _, r in exp.iterrows()}
    if len(exp) <= 100:
        assert gset == eset
    else:
        assert len(got) == 100 and gset <= eset


# --------------------------------------------- window functions


def test_q47_rank_lag_lead(session, F):
    """q47: windowed avg + rank, then self-joins at rn±1 (lag/lead)."""
    ss, dd, it, st = (F(t) for t in
                      ("store_sales", "date_dim", "item", "store"))
    dsel = dd[(dd.d_year == 2000)
              | ((dd.d_year == 1999) & (dd.d_moy == 12))
              | ((dd.d_year == 2001) & (dd.d_moy == 1))]
    m = ss.merge(dsel, left_on="ss_sold_date_sk", right_on="d_date_sk")
    m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    m = m.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    gk = ["i_category", "i_brand", "s_store_name", "s_company_name"]
    v1 = m.groupby(gk + ["d_year", "d_moy"], dropna=False).agg(
        sum_sales=("ss_sales_price", "sum")).reset_index()
    v1["avg_monthly"] = v1.groupby(
        gk + ["d_year"], dropna=False).sum_sales.transform("mean")
    # rank(): (d_year, d_moy) are group keys, so unique within partition
    v1 = v1.sort_values(gk + ["d_year", "d_moy"])
    v1["rn"] = v1.groupby(gk, dropna=False).cumcount() + 1
    # SQL equi-join drops NULL keys (pandas merge would match NaN=NaN)
    vj = v1.dropna(subset=gk)
    lag = vj[gk + ["rn", "sum_sales"]].rename(
        columns={"sum_sales": "psum"})
    lag["rn"] = lag.rn + 1
    lead = vj[gk + ["rn", "sum_sales"]].rename(
        columns={"sum_sales": "nsum"})
    lead["rn"] = lead.rn - 1
    v2 = vj.merge(lag, on=gk + ["rn"]).merge(lead, on=gk + ["rn"])
    v2 = v2[(v2.d_year == 2000) & (v2.avg_monthly > 0)]
    v2 = v2[(v2.sum_sales - v2.avg_monthly).abs()
            / v2.avg_monthly > 0.1]
    v2 = v2.sort_values(["sum_sales", "nsum"],
                        key=None).assign(
        diff=lambda d: d.sum_sales - d.avg_monthly)
    v2 = v2.sort_values(["diff", "nsum"]).head(100)
    got = run(session, 47)[-1]
    assert len(got) == len(v2)
    # compare the join keys in order plus the numeric columns
    for j, col in enumerate(gk):
        assert list(got.iloc[:, j]) == list(v2[col])
    np.testing.assert_allclose(
        _vals(got, got.columns[7]),
        (v2.sum_sales / 100).to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(
        _vals(got, got.columns[6]),
        (v2.avg_monthly / 100).to_numpy(), rtol=1e-9)


def test_q51_cumulative_fullouter(session, F):
    """q51: running sums, FULL OUTER join, running max, cross-compare."""
    dd = F("date_dim")
    dsel = dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)]

    def v1(fact, item_c, date_c, price_c):
        f = F(fact)
        m = f.merge(dsel, left_on=date_c, right_on="d_date_sk")
        m = m[m[item_c].notna()]
        g = m.groupby([item_c, "d_date"]).agg(
            s=(price_c, "sum")).reset_index().sort_values(
            [item_c, "d_date"])
        g["cume"] = g.groupby(item_c).s.cumsum()
        return g.rename(columns={item_c: "item_sk"})[
            ["item_sk", "d_date", "cume"]]

    web = v1("web_sales", "ws_item_sk", "ws_sold_date_sk",
             "ws_sales_price")
    store = v1("store_sales", "ss_item_sk", "ss_sold_date_sk",
               "ss_sales_price")
    x = web.merge(store, on=["item_sk", "d_date"], how="outer",
                  suffixes=("_w", "_s"))
    x = x.sort_values(["item_sk", "d_date"])
    x["web_cum"] = x.groupby("item_sk").cume_w.expanding().max(
    ).reset_index(level=0, drop=True)
    x["store_cum"] = x.groupby("item_sk").cume_s.expanding().max(
    ).reset_index(level=0, drop=True)
    y = x[x.web_cum > x.store_cum].sort_values(
        ["item_sk", "d_date"]).head(100)
    got = run(session, 51)[-1]
    assert len(got) == len(y)
    assert list(got.iloc[:, 0].astype(int)) == list(
        y.item_sk.astype(int))
    assert list(got.iloc[:, 1]) == list(pd.to_datetime(y.d_date, unit="D"))
    np.testing.assert_allclose(_vals(got, got.columns[4]),
                               (y.web_cum / 100).to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(_vals(got, got.columns[5]),
                               (y.store_cum / 100).to_numpy(), rtol=1e-9)


def test_q98_partition_ratio(session, F):
    """q98: revenue ratio over a class partition (no limit — full
    result compare)."""
    ss, it, dd = (F(t) for t in ("store_sales", "item", "date_dim"))
    lo, hi = _epoch("1999-02-22"), _epoch("1999-02-22") + 30
    m = ss.merge(it[it.i_category.isin(["Sports", "Books", "Home"])],
                 left_on="ss_item_sk", right_on="i_item_sk")
    m = m.merge(dd[(dd.d_date >= lo) & (dd.d_date <= hi)],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
    gk = ["i_item_id", "i_item_desc", "i_category", "i_class",
          "i_current_price"]
    g = m.groupby(gk, dropna=False).agg(
        rev=("ss_ext_sales_price", "sum")).reset_index()
    g["cls_tot"] = g.groupby("i_class", dropna=False).rev.transform(
        "sum")
    g["ratio"] = g.rev * 100 / g.cls_tot
    got = run(session, 98)[-1]
    assert len(got) == len(g)
    eset = sorted((r.i_item_id, round(r.rev / 100, 6),
                   round(r.ratio, 6)) for _, r in g.iterrows())
    gset = sorted((r.iloc[0], round(float(r.iloc[5]), 6),
                   round(float(r.iloc[6]), 6))
                  for _, r in got.iterrows())
    assert gset == eset


# ------------------------------------- outer joins / OR-branch joins


def test_q13_or_branch_demographics(session, F):
    """q13: OR-of-conjunction join residuals over three demographic
    branches (single-row aggregate output)."""
    ss, st, cd, hd, ca, dd = (F(t) for t in (
        "store_sales", "store", "customer_demographics",
        "household_demographics", "customer_address", "date_dim"))
    m = ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    m = m.merge(dd[dd.d_year == 2001], left_on="ss_sold_date_sk",
                right_on="d_date_sk")
    m = m.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    m = m.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    m = m.merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
    b1 = ((m.cd_marital_status == "M")
          & (m.cd_education_status == "Advanced Degree")
          & m.ss_sales_price.between(10000, 15000)
          & (m.hd_dep_count == 3))
    b2 = ((m.cd_marital_status == "S")
          & (m.cd_education_status == "College")
          & m.ss_sales_price.between(5000, 10000)
          & (m.hd_dep_count == 1))
    b3 = ((m.cd_marital_status == "W")
          & (m.cd_education_status == "2 yr Degree")
          & m.ss_sales_price.between(15000, 20000)
          & (m.hd_dep_count == 1))
    usa = m.ca_country == "United States"
    a1 = usa & m.ca_state.isin(["TX", "OH"]) \
        & m.ss_net_profit.between(10000, 20000)
    a2 = usa & m.ca_state.isin(["OR", "NM", "KY"]) \
        & m.ss_net_profit.between(15000, 30000)
    a3 = usa & m.ca_state.isin(["VA", "TX", "MS"]) \
        & m.ss_net_profit.between(5000, 25000)
    k = m[(b1 | b2 | b3) & (a1 | a2 | a3)]
    got = run(session, 13)[-1]
    r = got.iloc[0]
    exp = [k.ss_quantity.mean(), (k.ss_ext_sales_price / 100).mean(),
           (k.ss_ext_wholesale_cost / 100).mean(),
           (k.ss_ext_wholesale_cost / 100).sum()]
    for j, e in enumerate(exp):
        v = r.iloc[j]
        if len(k) == 0 or pd.isna(e):
            assert v is None or pd.isna(v)
        else:
            assert float(v) == pytest.approx(e, rel=1e-9)


def test_q40_left_outer_coalesce(session, F):
    """q40: fact LEFT OUTER JOIN returns (NULL keys on the build side)
    + coalesce + date-split conditional sums."""
    cs, cr, wh, it, dd = (F(t) for t in (
        "catalog_sales", "catalog_returns", "warehouse", "item",
        "date_dim"))
    pivot = _epoch("2000-03-11")
    m = cs.merge(cr[["cr_order_number", "cr_item_sk",
                     "cr_refunded_cash"]],
                 how="left", left_on=["cs_order_number", "cs_item_sk"],
                 right_on=["cr_order_number", "cr_item_sk"])
    m = m.merge(it[(it.i_current_price >= 99)
                   & (it.i_current_price <= 149)],
                left_on="cs_item_sk", right_on="i_item_sk")
    m = m.merge(wh, left_on="cs_warehouse_sk",
                right_on="w_warehouse_sk")
    m = m.merge(dd[(dd.d_date >= pivot - 30) & (dd.d_date <= pivot + 30)],
                left_on="cs_sold_date_sk", right_on="d_date_sk")
    diff = m.cs_sales_price - m.cr_refunded_cash.fillna(0)
    m = m.assign(
        before=np.where(m.d_date < pivot, diff, 0),
        after=np.where(m.d_date >= pivot, diff, 0))
    g = m.groupby(["w_state", "i_item_id"], dropna=False).agg(
        sb=("before", "sum"), sa=("after", "sum")).reset_index()
    g = g.sort_values(["w_state", "i_item_id"],
                      na_position="last").head(100)
    got = run(session, 40)[-1]
    assert len(got) == len(g)
    assert list(got.iloc[:, 0]) == list(g.w_state)
    assert list(got.iloc[:, 1]) == list(g.i_item_id)
    np.testing.assert_allclose(_vals(got, got.columns[2]),
                               (g.sb / 100).to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(_vals(got, got.columns[3]),
                               (g.sa / 100).to_numpy(), rtol=1e-9)


# --------------------------------------------- semi / anti joins


def test_q10_exists_and_in(session, F):
    """q10: EXISTS (semi join) AND IN over a UNION ALL subquery."""
    cu, ca, cd, ss, ws, cs, dd = (F(t) for t in (
        "customer", "customer_address", "customer_demographics",
        "store_sales", "web_sales", "catalog_sales", "date_dim"))
    dsel = dd[(dd.d_year == 2002) & (dd.d_moy >= 1) & (dd.d_moy <= 4)]
    dsk = set(dsel.d_date_sk)
    ss_cust = set(ss[ss.ss_sold_date_sk.isin(dsk)]
                  .ss_customer_sk.dropna())
    ws_cust = set(ws[ws.ws_sold_date_sk.isin(dsk)]
                  .ws_bill_customer_sk.dropna())
    cs_cust = set(cs[cs.cs_sold_date_sk.isin(dsk)]
                  .cs_ship_customer_sk.dropna())
    counties = ["Williamson County", "Walker County", "Ziebach County",
                "Franklin County", "Bronx County"]
    m = cu.merge(ca[ca.ca_county.isin(counties)],
                 left_on="c_current_addr_sk", right_on="ca_address_sk")
    m = m.merge(cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
    m = m[m.c_customer_sk.isin(ss_cust)
          & m.c_customer_sk.isin(ws_cust | cs_cust)]
    gk = ["cd_gender", "cd_marital_status", "cd_education_status",
          "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
          "cd_dep_employed_count", "cd_dep_college_count"]
    g = m.groupby(gk, dropna=False).size().reset_index(name="cnt")
    g = g.sort_values(gk, na_position="last").head(100)
    got = run(session, 10)[-1]
    assert len(got) == len(g)
    # output interleaves the 8 keys with 6 repeated counts
    assert list(got.cnt1.astype(int)) == list(g.cnt)
    assert list(got.cnt6.astype(int)) == list(g.cnt)
    for k in ("cd_gender", "cd_credit_rating", "cd_dep_count"):
        assert [None if pd.isna(x) else x for x in got[k]] == \
               [None if pd.isna(x) else x for x in g[k]]


def test_q16_exists_notexists(session, F):
    """q16: correlated EXISTS with <> residual + NOT EXISTS anti join +
    count(distinct)."""
    cs, dd, ca, cc, cr = (F(t) for t in (
        "catalog_sales", "date_dim", "customer_address", "call_center",
        "catalog_returns"))
    lo = _epoch("2002-02-01")
    dsel = dd[(dd.d_date >= lo) & (dd.d_date <= lo + 60)]
    m = cs.merge(dsel[["d_date_sk"]], left_on="cs_ship_date_sk",
                 right_on="d_date_sk")
    m = m.merge(ca[ca.ca_state == "GA"], left_on="cs_ship_addr_sk",
                right_on="ca_address_sk")
    m = m.merge(cc[cc.cc_county == "Williamson County"],
                left_on="cs_call_center_sk",
                right_on="cc_call_center_sk")
    # EXISTS cs2: same order, provably different warehouse (NULLs never
    # satisfy <>)
    wh = cs[["cs_order_number", "cs_warehouse_sk"]].dropna()
    per_order = wh.groupby("cs_order_number").cs_warehouse_sk.agg(
        ["nunique", "min", "max"])
    nun = m.cs_order_number.map(per_order["nunique"])
    only = m.cs_order_number.map(per_order["min"])
    # NULL <> x is UNKNOWN, so a NULL-warehouse cs1 row never satisfies
    # the EXISTS regardless of how many warehouses its order spans
    has_other = m.cs_warehouse_sk.notna() & (
        (nun >= 2) | ((nun == 1) & (only != m.cs_warehouse_sk)))
    returned = set(cr.cr_order_number)
    k = m[has_other.fillna(False)
          & ~m.cs_order_number.isin(returned)]
    got = run(session, 16)[-1]
    r = got.iloc[0]
    assert int(r.iloc[0]) == k.cs_order_number.nunique()
    for j, e in ((1, (k.cs_ext_ship_cost / 100).sum()),
                 (2, (k.cs_net_profit / 100).sum())):
        if len(k) == 0:  # SQL SUM over the empty set is NULL
            assert r.iloc[j] is None or pd.isna(r.iloc[j])
        else:
            assert float(r.iloc[j]) == pytest.approx(e, rel=1e-9)
    # the tiny SF can zero out the template's literals; drive the same
    # EXISTS-with-<>-residual / NOT EXISTS shape over non-empty data
    probe = session.sql(
        "select count(distinct cs_order_number), sum(cs_net_profit) "
        "from catalog_sales cs1 "
        "where exists (select * from catalog_sales cs2 "
        "  where cs1.cs_order_number = cs2.cs_order_number "
        "    and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk) "
        "and not exists (select * from catalog_returns cr1 "
        "  where cs1.cs_order_number = cr1.cr_order_number)"
    ).to_pandas()
    wh_ok = cs.cs_warehouse_sk.notna()
    other = cs[wh_ok].merge(
        per_order, left_on="cs_order_number", right_index=True)
    other = other[(other["nunique"] >= 2)
                  | (other["min"] != other.cs_warehouse_sk)]
    other = other[~other.cs_order_number.isin(returned)]
    assert len(other) > 0
    assert int(probe.iloc[0, 0]) == other.cs_order_number.nunique()
    assert float(probe.iloc[0, 1]) == pytest.approx(
        (other.cs_net_profit / 100).sum(), rel=1e-9)


# --------------------------------------------- except / YoY CTE


def test_q87_except_chain(session, F):
    """q87: chained EXCEPT over three DISTINCT channel sets."""
    s1 = _channel_cust(F, "store_sales", "ss_sold_date_sk",
                       "ss_customer_sk")
    s2 = _channel_cust(F, "catalog_sales", "cs_sold_date_sk",
                       "cs_bill_customer_sk")
    s3 = _channel_cust(F, "web_sales", "ws_sold_date_sk",
                       "ws_bill_customer_sk")
    exp = len((s1 - s2) - s3)
    got = run(session, 87)[-1]
    assert int(got.iloc[0, 0]) == exp


def test_q74_year_over_year(session, F):
    """q74: UNION ALL CTE self-joined 4 ways on customer, ratio
    comparison between channels (the q4/q11/q74 family shape)."""
    cu, ss, ws, dd = (F(t) for t in (
        "customer", "store_sales", "web_sales", "date_dim"))
    d99 = dd[dd.d_year.isin([1999, 2000])]

    def totals(fact, cust_c, date_c, paid_c):
        f = F(fact)
        m = f.merge(d99, left_on=date_c, right_on="d_date_sk")
        m = m.merge(cu, left_on=cust_c, right_on="c_customer_sk")
        return m.groupby(["c_customer_id", "d_year"]).agg(
            tot=(paid_c, "sum")).reset_index()

    s = totals("store_sales", "ss_customer_sk", "ss_sold_date_sk",
               "ss_net_paid")
    w = totals("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk",
               "ws_net_paid")

    def year(df, y):
        return df[df.d_year == y][["c_customer_id", "tot"]]

    j = year(s, 1999).rename(columns={"tot": "s1"}) \
        .merge(year(s, 2000).rename(columns={"tot": "s2"}),
               on="c_customer_id") \
        .merge(year(w, 1999).rename(columns={"tot": "w1"}),
               on="c_customer_id") \
        .merge(year(w, 2000).rename(columns={"tot": "w2"}),
               on="c_customer_id")
    j = j[(j.s1 > 0) & (j.w1 > 0)]
    # engine divides decimals as dollars; mirror exactly to keep
    # boundary rows identical
    j = j[(j.w2 / 100) / (j.w1 / 100) > (j.s2 / 100) / (j.s1 / 100)]
    exp = sorted(j.c_customer_id)[:100]
    got = run(session, 74)[-1]
    assert list(got.iloc[:, 0]) == exp


# ------------------------------- scalar subqueries / simple aggregates


def test_q9_case_over_scalars(session, F):
    """q9: five CASE branches each choosing between two scalar
    subqueries by a count threshold."""
    ss = F("store_sales")
    exp = []
    for lo in (1, 21, 41, 61, 81):
        b = ss[ss.ss_quantity.between(lo, lo + 19)]
        if len(b) > 3000:
            exp.append((b.ss_ext_discount_amt / 100).mean())
        else:
            exp.append((b.ss_net_paid / 100).mean())
    got = run(session, 9)[-1]
    rs = F("reason")
    n = int((rs.r_reason_sk == 1).sum())  # one output row per match
    assert len(got) == n
    for j, e in enumerate(exp):
        v = got.iloc[0, j]
        if pd.isna(e):
            assert v is None or pd.isna(v)
        else:
            assert float(v) == pytest.approx(e, rel=1e-9)


def test_q90_count_ratio(session, F):
    """q90: ratio of two uncorrelated COUNT(*) derived tables
    (cross join of 1-row subqueries + cast to double)."""
    ws, hd, td, wp = (F(t) for t in (
        "web_sales", "household_demographics", "time_dim", "web_page"))

    def leg(h0):
        m = ws.merge(td[(td.t_hour >= h0) & (td.t_hour <= h0 + 1)],
                     left_on="ws_sold_time_sk", right_on="t_time_sk")
        m = m.merge(hd[hd.hd_dep_count == 6],
                    left_on="ws_ship_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(wp[wp.wp_char_count.between(5000, 5200)],
                    left_on="ws_web_page_sk", right_on="wp_web_page_sk")
        return len(m)

    amc, pmc = leg(8), leg(19)
    got = run(session, 90)[-1]
    v = got.iloc[0, 0]
    if pmc == 0:  # division by zero -> NULL (SQL) per engine contract
        assert v is None or pd.isna(v) or np.isinf(float(v))
    else:
        assert float(v) == pytest.approx(amc / pmc, rel=1e-9)


def test_q96_filtered_count(session, F):
    """q96: single filtered-join COUNT(*) (the smoke-test shape)."""
    ss, hd, td, st = (F(t) for t in (
        "store_sales", "household_demographics", "time_dim", "store"))
    m = ss.merge(td[(td.t_hour == 20) & (td.t_minute >= 30)],
                 left_on="ss_sold_time_sk", right_on="t_time_sk")
    m = m.merge(hd[hd.hd_dep_count == 7], left_on="ss_hdemo_sk",
                right_on="hd_demo_sk")
    m = m.merge(st[st.s_store_name == "ese"], left_on="ss_store_sk",
                right_on="s_store_sk")
    got = run(session, 96)[-1]
    assert int(got.iloc[0, 0]) == len(m)


def test_q41_correlated_count_over_or_tree(session, F):
    """q41: correlated COUNT(*) > 0 (i.e. a semi join) against a deep
    OR-of-conjunctions predicate tree, plus DISTINCT."""
    it = F("item")
    w = it.i_category == "Women"
    mn = it.i_category == "Men"

    def band(cat, colors, units, sizes):
        return (cat & it.i_color.isin(colors) & it.i_units.isin(units)
                & it.i_size.isin(sizes))

    cond = (
        band(w, ["powder", "khaki"], ["Ounce", "Oz"],
             ["medium", "extra large"])
        | band(w, ["brown", "honeydew"], ["Bunch", "Ton"],
               ["N/A", "small"])
        | band(mn, ["floral", "deep"], ["N/A", "Dozen"],
               ["petite", "large"])
        | band(mn, ["light", "cornflower"], ["Box", "Pound"],
               ["medium", "extra large"])
        | band(w, ["midnight", "snow"], ["Pallet", "Gross"],
               ["medium", "extra large"])
        | band(w, ["cyan", "papaya"], ["Cup", "Dram"], ["N/A", "small"])
        | band(mn, ["orange", "frosted"], ["Each", "Tbl"],
               ["petite", "large"])
        | band(mn, ["forest", "ghost"], ["Lb", "Bundle"],
               ["medium", "extra large"]))
    hot_manufacts = set(it[cond].i_manufact.dropna())
    k = it[it.i_manufact_id.between(738, 778)
           & it.i_manufact.isin(hot_manufacts)]
    exp = sorted(set(k.i_product_name.dropna())
                 | ({None} if k.i_product_name.isna().any() else set()),
                 key=lambda x: (x is None, x))[:100]
    got = run(session, 41)[-1]
    assert [None if pd.isna(x) else x for x in got.iloc[:, 0]] == exp
