"""NDSJ301 negative: host-config branches and lax combinators only."""
import jax
import jax.numpy as jnp
from jax import lax

WIDE = True


@jax.jit
def host_config_branch(x):
    y = jnp.sum(x)
    if WIDE:  # module-level host config: static at trace time
        y = y * 2
    return jnp.where(y > 0, y, -y)


def combinator(x, enable):
    z = jnp.cumsum(x)
    return lax.cond(enable, lambda a: a, lambda a: -a, z)


prog = jax.jit(combinator)


def untraced_helper(n):
    assert n > 0  # plain host function: asserts freely
    return list(range(n))
