"""NDSJ303 negative (serve/): the coroutine awaits the engine thread;
no blocking sync is reachable from the loop."""


async def handle(req, engine):
    res = await engine.submit(req)
    return res
