"""NDSJ302 positive: traced builder captures a local the plan
fingerprint never folds in."""
import jax
import jax.numpy as jnp

from nds_tpu.cache import aot as cache_aot


def build(table, tables, scale):
    limit = scale * 2

    def fn(bufs):  # NDSJ302: captures `limit`, fingerprint-blind
        return jnp.minimum(jnp.sum(bufs["a"]), limit)

    pc, fp = cache_aot.try_fingerprint("kind", {"table": table},
                                       tables=tables)
    return jax.jit(fn), pc, fp
