"""NDSJ302 negative: the same capture, folded into the fingerprint."""
import jax
import jax.numpy as jnp

from nds_tpu.cache import aot as cache_aot


def build(table, tables, scale):
    limit = scale * 2

    def fn(bufs):  # capture covered: `limit` rides the fingerprint
        return jnp.minimum(jnp.sum(bufs["a"]), limit)

    pc, fp = cache_aot.try_fingerprint(
        "kind", {"table": table, "limit": limit}, tables=tables)
    return jax.jit(fn), pc, fp
