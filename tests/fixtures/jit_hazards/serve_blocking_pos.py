"""NDSJ303 positive (serve/): a blocking device sync reachable from a
coroutine through a same-module sync helper."""


def _finish(res):
    res.block_until_ready()  # NDSJ303: stalls the event loop via handle()
    return res


async def handle(req, engine):
    res = engine.run(req)
    return _finish(res)
