"""NDSJ301 positive: traced values leak into Python control flow."""
import jax
import jax.numpy as jnp


@jax.jit
def branch_on_sum(x):
    y = jnp.sum(x)
    if y > 0:  # NDSJ301: `if` on a traced value
        return y
    return -y


def loop_on_scan(x):
    t = jnp.cumsum(x)
    while t[0] < 3:  # NDSJ301: `while` on a traced value
        t = t + 1
    assert jnp.all(t > 0)  # NDSJ301: `assert` on a traced value
    return t


prog = jax.jit(loop_on_scan)
