"""NDSJ304 negative: the scalar stages explicitly before dispatch."""
import jax.numpy as jnp


def run(compiled, bufs, n):
    nchunk = jnp.int32(n)
    return compiled(bufs, nchunk)
