"""NDSJ303 positive: hidden scalarizations on device values in
dispatch code."""
import numpy as np


def dispatch(compiled, bufs):
    out = compiled(bufs)
    total = float(out)  # NDSJ303: blocking d2h sync
    host = np.asarray(out)  # NDSJ303: blocking d2h sync
    flag = out.item()  # NDSJ303: blocking d2h sync
    return total, host, flag
