"""NDSJ304 positive: bare numeric literal at the jit boundary."""


def run(compiled, bufs):
    return compiled(bufs, 512)  # NDSJ304: weak-typed scalar re-keys
