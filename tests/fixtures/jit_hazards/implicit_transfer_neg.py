"""NDSJ303 negative: the read-back batches through jax.device_get at
one sanctioned boundary."""
import jax


def dispatch(compiled, bufs):
    out = compiled(bufs)
    host = jax.device_get(out)
    return float(host[0])
