"""Double-buffered host<->device pipeline tests
(nds_tpu/engine/pipeline_io.py + its chunked-executor / scheduler /
power-loop / serve integrations; README "Pipelined execution"):
prefetcher ordering + cancellation + accounting, config resolution,
governor depth admission (depth demotes before placement), the hostile
paths (io.read fault inside the worker retried with the serial path's
bill, SIGTERM mid-prefetch draining to exit 75 with zero double
executions on resume, the ladder restoring depth and chunk_rows
together), and byte-identical results serial vs prefetch vs
query-boundary pipelining."""

import json
import os
import signal
import threading
import time

import pytest

from nds_tpu.engine import pipeline_io, scheduler
from nds_tpu.engine.pipeline_io import ChunkPrefetcher
from nds_tpu.engine.scheduler import (
    CHUNKED, CPU, DEVICE, ExecutionPipeline, MemoryGovernor,
    make_pipeline,
)
from nds_tpu.engine.session import Session
from nds_tpu.obs import memwatch
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.resilience import drain, faults
from nds_tpu.resilience.faults import InjectedOOM
from nds_tpu.utils import power_core
from nds_tpu.utils.config import EngineConfig


@pytest.fixture(autouse=True)
def _clear_faults():
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------------- the prefetcher

class TestChunkPrefetcher:
    def _stage(self, log=None, fail_at=None, sleep=0.0):
        def stage(item):
            if fail_at is not None and item == fail_at:
                raise RuntimeError(f"staging broke at {item}")
            if sleep:
                time.sleep(sleep)
            if log is not None:
                log.append(item)
            return {"chunk": item}, 64
        return stage

    @pytest.mark.parametrize("depth", [0, 1, 2, 4])
    def test_in_order_delivery_any_depth(self, depth):
        log = []
        pf = ChunkPrefetcher(range(7), self._stage(log), depth)
        got = []
        for staged in pf:
            got.append(staged.item)
            assert staged.payload == {"chunk": staged.item}
            staged.release()
        stats = pf.close()
        assert got == list(range(7))
        assert log == list(range(7))  # staged in order too
        assert stats["staged"] == 7
        assert stats["depth"] == depth

    def test_depth_bounds_staged_ahead(self):
        outstanding = {"now": 0, "max": 0}
        lock = threading.Lock()

        def stage(item):
            with lock:
                outstanding["now"] += 1
                outstanding["max"] = max(outstanding["max"],
                                         outstanding["now"])
            return {"i": item}, 32

        pf = ChunkPrefetcher(range(16), stage, 2)
        for staged in pf:
            time.sleep(0.005)  # slow consumer: let the worker run ahead
            with lock:
                outstanding["now"] -= 1
            staged.release()
        pf.close()
        # at most depth chunks staged-but-unconsumed + the one the
        # consumer holds
        assert outstanding["max"] <= 3

    def test_stage_error_surfaces_in_chunk_order(self):
        pf = ChunkPrefetcher(range(5), self._stage(fail_at=2), 2)
        got = []
        with pytest.raises(RuntimeError, match="staging broke at 2"):
            for staged in pf:
                got.append(staged.item)
                staged.release()
        pf.close()
        assert got == [0, 1]

    def test_close_cancels_at_chunk_boundary(self):
        log = []
        pf = ChunkPrefetcher(range(64), self._stage(log, sleep=0.01), 2)
        first = next(pf)
        first.release()
        pf.close()
        # the worker stopped at a chunk boundary instead of staging
        # all 64
        assert 1 <= len(log) < 64

    def test_unconsumed_staged_bytes_release_on_close(self):
        base = memwatch.TRACKER._live
        pf = ChunkPrefetcher(range(8), self._stage(sleep=0.002), 2)
        staged = next(pf)
        staged.release()
        pf.close()
        assert memwatch.TRACKER._live == base

    def test_release_is_pop_once(self):
        base = memwatch.TRACKER._live
        pf = ChunkPrefetcher([0], self._stage(), 0)
        staged = next(pf)
        staged.release()
        staged.release()
        pf.close()
        assert memwatch.TRACKER._live == base

    def test_wait_plus_hidden_equals_staging(self):
        pf = ChunkPrefetcher(range(6), self._stage(sleep=0.01), 2)
        for staged in pf:
            staged.release()
        stats = pf.close()
        assert stats["stage_s"] > 0
        assert stats["wait_s"] + stats["hidden_s"] == pytest.approx(
            stats["stage_s"], rel=0.35, abs=0.05)

    def test_serial_depth0_has_no_worker_and_no_wait(self):
        pf = ChunkPrefetcher(range(3), self._stage(), 0)
        assert pf._thread is None
        for staged in pf:
            staged.release()
        stats = pf.close()
        assert stats["wait_s"] == 0.0 and stats["hidden_s"] == 0.0

    def test_fault_context_republishes_on_worker(self):
        faults.install("io.read:fault@ctxq7")
        with faults.context(query="ctxq7"):
            pf = ChunkPrefetcher(range(3), self._stage(), 2)
        with pytest.raises(faults.InjectedTransientFault):
            for staged in pf:
                staged.release()
        pf.close()

    def test_io_read_fires_inline_on_serial_path(self):
        faults.install("io.read:fault@serialq")
        with faults.context(query="serialq"):
            pf = ChunkPrefetcher(range(3), self._stage(), 0)
            with pytest.raises(faults.InjectedTransientFault):
                for staged in pf:
                    staged.release()
            pf.close()


# --------------------------------------------------- config resolution

class TestConfig:
    def test_default_depth(self, monkeypatch):
        monkeypatch.delenv(pipeline_io.PREFETCH_ENV, raising=False)
        assert pipeline_io.resolve_depth() == pipeline_io.DEFAULT_DEPTH

    def test_env_off_and_depth(self, monkeypatch):
        monkeypatch.setenv(pipeline_io.PREFETCH_ENV, "off")
        assert pipeline_io.resolve_depth() == 0
        monkeypatch.setenv(pipeline_io.PREFETCH_ENV, "3")
        assert pipeline_io.resolve_depth() == 3

    def test_config_beats_env(self, monkeypatch):
        monkeypatch.setenv(pipeline_io.PREFETCH_ENV, "7")
        cfg = EngineConfig(overrides={"engine.prefetch.enabled": "off"})
        assert pipeline_io.resolve_depth(cfg) == 0
        cfg = EngineConfig(overrides={"engine.prefetch.depth": "1"})
        assert pipeline_io.resolve_depth(cfg) == 1
        cfg = EngineConfig(overrides={"engine.prefetch.enabled": "on"})
        assert pipeline_io.resolve_depth(cfg) \
            == pipeline_io.DEFAULT_DEPTH

    def test_bad_depth_raises(self):
        cfg = EngineConfig(overrides={"engine.prefetch.depth": "two"})
        with pytest.raises(ValueError):
            pipeline_io.resolve_depth(cfg)

    def test_boundary_default_off_and_master_switch(self, monkeypatch):
        monkeypatch.delenv(pipeline_io.BOUNDARY_ENV, raising=False)
        monkeypatch.delenv(pipeline_io.PREFETCH_ENV, raising=False)
        assert not pipeline_io.boundary_enabled()
        cfg = EngineConfig(overrides={"engine.prefetch.boundary": "on"})
        assert pipeline_io.boundary_enabled(cfg)
        # prefetch off is the master off switch
        cfg = EngineConfig(overrides={"engine.prefetch.boundary": "on",
                                      "engine.prefetch.enabled": "off"})
        assert not pipeline_io.boundary_enabled(cfg)

    def test_chunk_working_set_scales_by_chunk_fraction(self):
        from nds_tpu.analysis.plan_verify import PlanEstimate
        est = PlanEstimate(tables={"t": (1_000_000, 8_000_000),
                                   "dim": (100, 1_000)})
        # 1/10th of the big table's rows -> 1/10th of its bytes
        assert pipeline_io.chunk_working_set(est, 100_000) == 800_000
        # chunks larger than the table cost the whole table
        assert pipeline_io.chunk_working_set(est, 1 << 40) == 8_000_000


# ------------------------------------------- governor depth admission

class TestGovernorDepthAdmission:
    def test_admit_prefetch_demotes_depth_not_placement(self,
                                                        monkeypatch):
        from nds_tpu.analysis.plan_verify import PlanEstimate
        monkeypatch.setattr(memwatch, "live_bytes", lambda: 0)
        est = PlanEstimate(bytes=4_000_000,
                           tables={"t": (1_000_000, 4_000_000)})
        # base projection = 4M x EXPANSION(2.0) = 8M
        gov = MemoryGovernor(budget=8_500_000)
        # 1M-byte chunks: depth 2 needs 10M (> budget), depth 0 fits
        assert gov.admit_prefetch(est, 1_000_000, 2) == 0
        # a roomier budget admits depth 1 but not 2
        gov = MemoryGovernor(budget=9_500_000)
        assert gov.admit_prefetch(est, 1_000_000, 2) == 1
        # nothing constrains: depth unchanged
        gov = MemoryGovernor(budget=1 << 40)
        assert gov.admit_prefetch(est, 1_000_000, 2) == 2

    def _pipe(self, budget: int, monkeypatch):
        from nds_tpu.analysis import plan_verify
        monkeypatch.setattr(memwatch, "live_bytes", lambda: 0)
        est = plan_verify.PlanEstimate(
            bytes=4_000_000, tables={"t": (1_000_000, 4_000_000)})
        monkeypatch.setattr(plan_verify, "estimate_plan",
                            lambda *a, **k: est)
        cfg = EngineConfig(overrides={
            "engine.backend": "tpu",
            "engine.placement.force": "chunked",
            "engine.chunk_rows": str(250_000),  # 1M-byte chunks
            "engine.prefetch.depth": "2",
            "engine.placement.device_budget_bytes": str(budget),
            "engine.retry.base_delay_s": "0"})
        pipe = ExecutionPipeline(backend="tpu", config=cfg)
        pipe({})

        class ChunkedStub:
            prefetch_depth = 2
            chunk_rows = 250_000
            stream_bytes = 1 << 40
            last_timings = {"execute_ms": 1.0}
            last_query_span = None

            def __init__(self):
                self.seen = []

            def execute(self, planned, key=None):
                self.seen.append((self.prefetch_depth,
                                  self.chunk_rows))
                return "ok"

        stub = ChunkedStub()
        pipe._executors[CHUNKED] = stub
        return pipe, stub

    def test_budget_admitting_serial_but_not_depth2_demotes_depth(
            self, monkeypatch):
        # base projection 8M fits an 8.5M budget; +2x1M chunks does
        # not -> the DEPTH demotes (to 0), the placement does not
        pipe, stub = self._pipe(8_500_000, monkeypatch)
        planned, _cat = _plan_h()
        before = obs_metrics.counter(
            "prefetch_depth_demotions_total").value
        assert pipe.execute(planned) == "ok"
        assert pipe.last_schedule["placement"] == CHUNKED
        assert pipe.last_schedule["prefetch_depth"] == 0
        # the stub EXECUTED at the demoted depth...
        assert stub.seen == [(0, 250_000)]
        # ...and the per-query restore rolled it back
        assert stub.prefetch_depth == 2
        assert obs_metrics.counter(
            "prefetch_depth_demotions_total").value == before + 1

    def test_roomy_budget_leaves_depth_alone(self, monkeypatch):
        pipe, stub = self._pipe(1 << 40, monkeypatch)
        planned, _cat = _plan_h()
        assert pipe.execute(planned) == "ok"
        assert "prefetch_depth" not in pipe.last_schedule
        assert stub.seen == [(2, 250_000)]

    def test_restores_unwind_through_a_mid_query_ladder_walk(
            self, monkeypatch):
        """The admission's depth restore survives a ladder walk OUT of
        the chunked rung mid-query (the _restore list unwinds LIFO in
        _run_ladder's finally, so stacked entries for one attribute —
        should a future path create them — land on the ORIGINAL value,
        never an intermediate)."""
        pipe, stub = self._pipe(9_500_000, monkeypatch)  # admits depth 1

        class CpuStub:
            last_timings = {"execute_ms": 1.0}
            last_query_span = None

            def execute(self, planned, key=None):
                return "ok"

        pipe._executors[CPU] = CpuStub()
        fails = [InjectedOOM("device.execute",
                             "injected RESOURCE_EXHAUSTED: oom")]
        real_execute = type(stub).execute

        def flaky_execute(self, planned, key=None):
            if fails:
                raise fails.pop(0)
            return real_execute(self, planned, key)

        monkeypatch.setattr(type(stub), "execute", flaky_execute)
        planned, _cat = _plan_h()
        assert pipe.execute(planned) == "ok"
        # OOM at chunked(depth 1) stepped to the relief re-entry...
        # whatever the walk did mid-query, the executor came back to
        # its CONFIGURED values afterwards
        assert stub.prefetch_depth == 2
        assert stub.chunk_rows == 250_000


def _plan_h(sql="select count(*) as c from lineitem"):
    sess = Session.for_nds_h()
    return sess.plan(sql), sess.catalog


# ------------------------------------------------- ladder restore pair

class TestLadderRestoresDepthAndChunkTogether:
    def test_chunked_relief_entry_runs_serial_then_restores(self):
        class FakeDev:
            last_timings = {"execute_ms": 1.0}
            last_query_span = None

            def execute(self, planned, key=None):
                raise InjectedOOM("device.execute",
                                  "injected RESOURCE_EXHAUSTED: oom")

        class FakeChunked:
            prefetch_depth = 2
            chunk_rows = 1 << 20
            stream_bytes = 1 << 40
            last_timings = {"execute_ms": 1.0}
            last_query_span = None

            def __init__(self):
                self.seen = []

            def execute(self, planned, key=None):
                self.seen.append((self.prefetch_depth,
                                  self.chunk_rows))
                return "ok"

        cfg = EngineConfig(overrides={
            "engine.backend": "tpu",
            "engine.placement.governor": "off",
            "engine.retry.base_delay_s": "0"})
        pipe = ExecutionPipeline(backend="tpu", config=cfg)
        pipe({})
        dev, chk = FakeDev(), FakeChunked()
        pipe._executors[DEVICE] = dev
        pipe._executors[CHUNKED] = chk
        pipe._executors[CPU] = FakeChunked()
        planned, _cat = _plan_h("select count(*) as c from nation")
        assert pipe.execute(planned) == "ok"
        # the relief entry ran THIS query serial at half the chunk...
        assert chk.seen == [(0, 1 << 19)]
        # ...and depth + chunk_rows rolled back TOGETHER afterwards
        assert chk.prefetch_depth == 2
        assert chk.chunk_rows == 1 << 20


# ------------------------------------ chunked end-to-end (real engine)

@pytest.fixture(scope="module")
def h_tables():
    from nds_tpu.datagen import tpch as gen_h
    from nds_tpu.io.host_table import from_arrays
    from nds_tpu.nds_h.schema import get_schemas
    schemas = get_schemas()
    return {n: from_arrays(n, schemas[n], gen_h.gen_table(n, 0.01))
            for n in ("lineitem", "orders", "customer", "nation",
                      "region", "part", "supplier", "partsupp")}


Q6 = ("select sum(l_extendedprice * l_discount) as revenue from "
      "lineitem where l_shipdate >= date '1994-01-01' and l_shipdate"
      " < date '1995-01-01' and l_discount between 0.05 and 0.07 and"
      " l_quantity < 24")


def _chunked_pipe(h_tables, depth: int, extra: "dict | None" = None):
    cfg = EngineConfig(overrides={
        "engine.backend": "tpu",
        "engine.placement.force": "chunked",
        "engine.stream_bytes": "50000",
        "engine.chunk_rows": "4096",
        "engine.prefetch.depth": str(depth),
        "engine.retry.base_delay_s": "0",
        **(extra or {})})
    pipe = make_pipeline(cfg, "tpu")
    sess = Session.for_nds_h(pipe)
    for t in h_tables.values():
        sess.register_table(t)
    return sess, pipe


class TestChunkedPrefetchE2E:
    def test_rows_identical_and_attribution_published(self, h_tables):
        from nds_tpu.io.result_io import result_digest
        sess0, _p0 = _chunked_pipe(h_tables, 0)
        sess2, p2 = _chunked_pipe(h_tables, 2)
        d0 = result_digest(sess0.sql(Q6))
        d2 = result_digest(sess2.sql(Q6))
        assert d0 == d2
        from nds_tpu import obs
        timings = obs.query_timings(p2)
        assert timings.get("prefetch_depth") == 2
        assert timings.get("prefetch_hidden_s", -1) >= 0
        assert timings.get("prefetch_wait_ms", -1) >= 0
        # serial timings carry NO prefetch keys (byte-identical
        # pre-pipeline surface)
        sess0b, p0b = _chunked_pipe(h_tables, 0)
        result_digest(sess0b.sql(Q6))
        assert not any(k.startswith("prefetch")
                       for k in obs.query_timings(p0b))

    def test_io_read_fault_in_worker_retried_like_serial(self,
                                                         h_tables):
        """The hostile path: an injected io.read fault fires ON THE
        PREFETCH WORKER, surfaces at the consumer in chunk order, and
        the pipeline retries it to Completed with exactly the serial
        path's retry bill."""
        from nds_tpu.io.result_io import result_digest
        bills = {}
        for depth in (0, 2):
            sess, pipe = _chunked_pipe(h_tables, depth)
            faults.install("io.read:fault@lineitem")
            with faults.context(query=f"q6-depth{depth}"):
                digest = result_digest(sess.sql(Q6))
            faults.clear()
            st = pipe.last_stats
            assert st.gave_up_reason is None
            bills[depth] = (st.retries, digest)
        # retried to Completed with the SAME bill on both paths
        assert bills[0] == bills[2]
        assert bills[2][0] == 1


# ------------------------------- SIGTERM mid-prefetch: drain + resume

@pytest.fixture(scope="module")
def h_stream_dir(tmp_path_factory, h_tables):
    """Raw NDS-H warehouse + 3-query stream for power-loop runs."""
    from nds_tpu.nds_h import gen_data
    root = tmp_path_factory.mktemp("pipeio")
    raw = str(root / "raw")
    gen_data.generate_data_local(0.01, 2, raw, workers=2)
    from nds_tpu.nds_h import streams as hstreams
    spath = str(root / "streams" / "stream.sql")
    os.makedirs(os.path.dirname(spath), exist_ok=True)
    parts = [f"-- Template file: {qn}\n\n"
             f"{hstreams.render_query(qn, None, stream=0)}\n"
             for qn in (1, 3, 6)]
    with open(spath, "w") as f:
        f.write("\n".join(parts))
    return {"raw": raw, "stream": spath}


def _stream_cfg(extra: "dict | None" = None) -> EngineConfig:
    return EngineConfig(overrides={
        "engine.backend": "tpu",
        "engine.placement.force": "chunked",
        "engine.stream_bytes": "50000",
        "engine.chunk_rows": "4096",
        "engine.prefetch.depth": "2",
        "engine.retry.base_delay_s": "0",
        **(extra or {})})


class TestDrainMidPrefetch:
    @pytest.mark.slow
    def test_sigterm_mid_prefetch_exits_75_zero_double_execution(
            self, h_stream_dir, tmp_path):
        from nds_tpu.nds_h.power import SUITE
        from nds_tpu.resilience.journal import QueryJournal
        jsons = str(tmp_path / "json")
        jpath = os.path.join(jsons, "power-nds_h_queries.json")
        # slow query3's chunk staging so the prefetch worker is
        # genuinely mid-flight when the signal lands
        faults.install("io.read:delay=0.08@q3")

        def _fire():
            # wait until the journal shows query3 STARTED (dispatched),
            # then signal while its prefetch worker is staging
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    with open(jpath) as f:
                        doc = json.load(f)
                    if (doc.get("queries", {}).get("query3", {})
                            .get("starts")):
                        break
                except (OSError, ValueError):
                    pass
                time.sleep(0.02)  # ndslint: disable=NDS108 -- deadline-bounded journal poll, not a retry loop
            time.sleep(0.2)
            os.kill(os.getpid(), signal.SIGTERM)

        killer = threading.Thread(target=_fire, daemon=True)
        killer.start()
        with pytest.raises(SystemExit) as ei:
            power_core.run_query_stream(
                SUITE, h_stream_dir["raw"], h_stream_dir["stream"],
                str(tmp_path / "t.csv"), config=_stream_cfg(),
                input_format="raw", json_summary_folder=jsons)
        killer.join(timeout=60)
        assert ei.value.code == drain.EXIT_RESUMABLE == 75
        faults.clear()
        j = QueryJournal(jpath)
        assert j.load()
        done = j.completed()
        # the in-flight query FINISHED under the drain; the rest never
        # started
        assert "query3" in done
        assert "query6" not in done
        # resume: only the unfinished statements run, nothing twice
        failures = power_core.run_query_stream(
            SUITE, h_stream_dir["raw"], h_stream_dir["stream"],
            str(tmp_path / "t2.csv"), config=_stream_cfg(),
            input_format="raw", json_summary_folder=jsons,
            resume=True)
        assert failures == 0
        j2 = QueryJournal(jpath)
        assert j2.load()
        done = j2.completed()
        assert sorted(done) == ["query1", "query3", "query6"]
        for q, e in done.items():
            # zero double executions: every statement completed from
            # exactly one start per incarnation that ran it
            assert len(e["starts"]) == len(set(e["starts"]))
            if q in ("query1", "query3"):
                assert e["starts"] == [0]       # first incarnation only
            else:
                assert e["starts"] == [1]       # resumed incarnation


# ----------------------------------------- query-boundary pipelining

class TestBoundaryPipelining:
    @pytest.mark.slow
    def test_power_loop_boundary_rows_and_journal_identical(
            self, h_stream_dir, tmp_path):
        from nds_tpu.nds_h.power import SUITE

        def run(label, extra):
            jsons = str(tmp_path / f"json_{label}")
            failures = power_core.run_query_stream(
                SUITE, h_stream_dir["raw"], h_stream_dir["stream"],
                str(tmp_path / f"{label}.csv"), config=_stream_cfg(
                    extra), input_format="raw",
                json_summary_folder=jsons)
            assert failures == 0
            out = {}
            from nds_tpu.obs import analyze
            for s in analyze.load_summaries(jsons):
                out[s["query"]] = s
            return out

        plain = run("plain", {})
        bnd = run("boundary", {"engine.prefetch.boundary": "on"})
        assert sorted(plain) == sorted(bnd) == ["query1", "query3",
                                                "query6"]
        for q in plain:
            assert plain[q]["result_digest"] == bnd[q]["result_digest"]
            assert bnd[q]["queryStatus"] == ["Completed"]
        # the overlapped brackets still validate against the summary
        # schema
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        from check_trace_schema import validate_summary
        for s in bnd.values():
            assert validate_summary(s) == []

    def test_ndslint_nds117_blocking_transfer_fixtures(self):
        """NDS117 per-rule fixture pair: blocking transfers inside a
        chunk-stream loop flag; host-slice asarray, out-of-loop syncs,
        other modules, and waived sanctioned sync points do not."""
        from nds_tpu.analysis import lint_rules

        def lint(src, path="nds_tpu/engine/chunked_exec.py"):
            return lint_rules.lint_sources({path: src},
                                           enabled={"NDS117"})

        bad = ("import jax\nimport numpy as np\n\n"
               "def scan(chunks, compiled, dev):\n"
               "    for bufs in chunks:\n"
               "        out = jax.device_get(compiled(bufs))\n"
               "        dev.block_until_ready()\n"
               "        keep = np.asarray(compiled(bufs))\n")
        res = lint(bad)
        assert [v.rule for v in res.violations] == ["NDS117"] * 3
        # the prefetch worker module is in scope too
        assert lint(bad,
                    path="nds_tpu/engine/pipeline_io.py").violations
        # other engine modules are out of scope (the base executor's
        # _finish IS the sanctioned sync point of its own contract)
        assert lint(bad,
                    path="nds_tpu/engine/device_exec.py"
                    ).violations == []
        clean = ("import numpy as np\n\n"
                 "def stage(chunks, col):\n"
                 "    for s, e in chunks:\n"
                 "        sl = np.asarray(col.values[s:e])\n"  # host slice
                 "    return sl\n")
        assert lint(clean).violations == []
        outside = ("import jax\n\n"
                   "def finish(devs):\n"
                   "    return jax.device_get(devs)\n")
        assert lint(outside).violations == []
        waived = ("import jax\n\n"
                  "def scan(chunks, compiled):\n"
                  "    for bufs in chunks:\n"
                  "        # ndslint: waive[NDS117] -- sanctioned per-chunk sync: the verdict gates the loop\n"
                  "        out = jax.device_get(compiled(bufs))\n")
        res = lint(waived)
        assert res.violations == [] and len(res.waived) == 1

    def test_serve_boundary_overlap_digest_identical(self, h_tables):
        from nds_tpu.serve.server import QueryServer
        results = {}
        for label, overrides in (
                ("sync", {}),
                ("boundary", {"engine.prefetch.boundary": "on"})):
            cfg = EngineConfig(overrides={"engine.backend": "cpu",
                                          **overrides})
            srv = QueryServer(config=cfg)
            for t in h_tables.values():
                srv.register_table(t, suite="nds_h")
            srv.start()
            try:
                futs = [srv.submit("tenant-a", "nds_h", Q6,
                                   qname=f"q6-{i}")
                        for i in range(4)]
                results[label] = [f.result(timeout=120) for f in futs]
            finally:
                srv.stop()
        for a, b in zip(results["sync"], results["boundary"]):
            assert a.status == b.status == "ok"
            assert a.digest == b.digest
