"""Resilience layer tests: seeded fault injection (hang/corrupt kinds
included), failure classification, RetryPolicy backoff/deadline
semantics (mid-attempt deadline checks included), the power-loop retry
+ fallback integration, thread-safe failure collection, the NDS108/
NDS109 lint rules, the resumable bench journal (torn-journal
degradation included), chunked-executor OOM degradation, throughput
stream failure reports, the heartbeat watchdog + stall reports, the
stream supervisor's restart-once semantics, and artifact digest
verification."""

import json
import os
import sys
import threading
import time

import pytest

from nds_tpu.analysis import lint_rules
from nds_tpu.io import integrity
from nds_tpu.nds import gen_data, streams
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.resilience import faults, supervise, watchdog
from nds_tpu.resilience.journal import (
    JournalMismatch, PhaseJournal, config_digest,
)
from nds_tpu.resilience.retry import (
    DETERMINISTIC, TRANSIENT, QueryDeadlineExceeded, RetryPolicy,
    RetryStats, check_deadline, classify, deadline_scope, is_oom,
)
from nds_tpu.utils import power_core
from nds_tpu.utils.config import EngineConfig


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def mini_wh(tmp_path_factory):
    """Tiny raw NDS warehouse + a 3-query stream (raw format: the
    power loop reads .dat directly, no transcode needed)."""
    root = tmp_path_factory.mktemp("resilience")
    raw = str(root / "raw")
    gen_data.generate_data_local(0.01, 2, raw, workers=2)
    sdir = str(root / "streams")
    streams.generate_query_streams(sdir, 1, templates=[96, 7, 93])
    return {"raw": raw, "stream": os.path.join(sdir, "query_0.sql"),
            "root": str(root)}


# ------------------------------------------------------- fault harness

class TestFaultSchedule:
    def test_parse_full_syntax(self):
        specs = faults.parse_schedule(
            "device.execute:oom@q5,io.read:delay=0.2@*,"
            "exchange:fault*3~0.5@query1*")
        assert [s.site for s in specs] == ["device.execute", "io.read",
                                          "exchange"]
        assert specs[0].times == 1          # raising kinds default once
        assert specs[1].times is None       # delay defaults unlimited
        assert specs[1].param == 0.2
        assert specs[2].times == 3 and specs[2].prob == 0.5

    @pytest.mark.parametrize("bad", [
        "nonsense", "plan:oom",             # missing scope
        "bogus.site:oom@*",                 # unknown site
        "plan:explode@*",                   # unknown kind
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            faults.parse_schedule(bad)

    def test_scope_q_alias_and_fnmatch(self):
        assert faults._scope_matches("q5", {"query": "query5"})
        assert not faults._scope_matches("q5", {"query": "query55"})
        assert faults._scope_matches("query5*", {"query": "query55"})
        assert faults._scope_matches("*", {})
        assert faults._scope_matches("store_*", {"table": "store_sales"})

    def test_times_budget_lets_retry_succeed(self):
        faults.install("plan:oom@*")
        with pytest.raises(faults.InjectedOOM):
            faults.fault_point("plan")
        faults.fault_point("plan")  # budget spent: the retry passes

    def test_context_and_suppress(self):
        faults.install("device.execute:fault@q7")
        faults.fault_point("device.execute")  # no context: no match
        with faults.context(query="query7"):
            with faults.suppress():
                faults.fault_point("device.execute")  # warmup analog
            with pytest.raises(faults.InjectedTransientFault):
                faults.fault_point("device.execute")

    def test_probability_replays_from_seed(self):
        def firing_pattern(seed):
            plan = faults.install("plan:fault*999~0.4@*", seed=seed)
            fired = []
            for _ in range(40):
                try:
                    faults.fault_point("plan")
                    fired.append(0)
                except faults.InjectedTransientFault:
                    fired.append(1)
            faults.clear()
            return fired, plan.specs[0].fired

        a, na = firing_pattern(3)
        b, nb = firing_pattern(3)
        c, _ = firing_pattern(4)
        assert a == b and na == nb      # exact replay from the seed
        assert 0 < na < 40              # probabilistic, not all-or-none
        assert a != c                   # the seed actually matters

    def test_env_schedule_and_zero_cost_unset(self, monkeypatch):
        monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
        faults.clear()
        faults.fault_point("plan")      # unset: pure no-op
        monkeypatch.setenv(faults.FAULTS_ENV, "plan:deterministic@*")
        with pytest.raises(faults.InjectedDeterministicFault):
            faults.fault_point("plan")

    def test_env_seed_change_rebuilds_plan(self, monkeypatch):
        """The env cache keys on (schedule, seed): changing only the
        seed must rebuild the plan (fresh fired-counts, new RNG)."""
        monkeypatch.setenv(faults.FAULTS_ENV, "plan:fault*999~0.5@*")
        monkeypatch.setenv(faults.SEED_ENV, "1")
        faults.clear()

        def pattern():
            fired = []
            for _ in range(30):
                try:
                    faults.fault_point("plan")
                    fired.append(0)
                except faults.InjectedTransientFault:
                    fired.append(1)
            return fired

        a = pattern()
        monkeypatch.setenv(faults.SEED_ENV, "2")
        b = pattern()
        assert a != b                   # new seed actually took effect
        monkeypatch.setenv(faults.SEED_ENV, "1")
        assert pattern() == a           # and replays exactly again


# ------------------------------------------------------ classification

class TestClassify:
    def test_vocabulary(self):
        assert classify(faults.InjectedOOM("x", "boom")) == TRANSIENT
        assert classify(
            faults.InjectedTransientFault("x", "boom")) == TRANSIENT
        assert classify(
            faults.InjectedDeterministicFault("x", "boom")) \
            == DETERMINISTIC
        # jaxlib's device-OOM message shape
        assert classify(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 1 bytes")) \
            == TRANSIENT
        from nds_tpu.engine.device_exec import DeviceExecError
        assert classify(DeviceExecError(
            "exchange overflow persisted after retries")) == TRANSIENT
        # parse/plan/verify-style errors never retry
        assert classify(ValueError("no such column")) == DETERMINISTIC
        assert classify(KeyError("tbl")) == DETERMINISTIC

    def test_is_oom(self):
        assert is_oom(faults.InjectedOOM("x", "injected"))
        assert is_oom(RuntimeError("RESOURCE_EXHAUSTED: ..."))
        assert is_oom(RuntimeError("Out of memory allocating"))
        assert not is_oom(faults.InjectedTransientFault("x", "generic"))


# -------------------------------------------------------- retry policy

class TestRetryPolicy:
    def _policy(self, **kw):
        kw.setdefault("sleep", lambda d: None)
        return RetryPolicy(**kw)

    def test_transient_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise faults.InjectedOOM("s", "injected oom")
            return "ok"

        st = RetryStats()
        before = obs_metrics.snapshot()
        assert self._policy(max_attempts=3).call(flaky, stats=st) == "ok"
        assert st.attempts == 3 and st.retries == 2
        assert st.gave_up_reason is None
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["query_retries_total"] == 2

    def test_deterministic_never_retries(self):
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("planner bug")

        st = RetryStats()
        with pytest.raises(ValueError):
            self._policy(max_attempts=5).call(broken, stats=st)
        assert len(calls) == 1 and st.retries == 0
        assert st.gave_up_reason == "deterministic"

    def test_attempt_cap_exhausts(self):
        def always():
            raise faults.InjectedOOM("s", "injected oom")

        st = RetryStats()
        with pytest.raises(faults.InjectedOOM):
            self._policy(max_attempts=3).call(always, stats=st)
        assert st.attempts == 3
        assert st.gave_up_reason == "attempts_exhausted(3)"

    def test_backoff_exponential_jittered_seeded(self):
        p1 = self._policy(base_delay_s=0.1, max_delay_s=10.0,
                          jitter=0.25, seed=11)
        p2 = self._policy(base_delay_s=0.1, max_delay_s=10.0,
                          jitter=0.25, seed=11)
        p3 = self._policy(base_delay_s=0.1, max_delay_s=10.0,
                          jitter=0.25, seed=12)
        d1 = [p1.delay_for(i) for i in range(5)]
        assert d1 == [p2.delay_for(i) for i in range(5)]  # seeded
        assert d1 != [p3.delay_for(i) for i in range(5)]
        for i, d in enumerate(d1):
            base = 0.1 * 2 ** i
            assert base <= d <= base * 1.25     # exp + bounded jitter
        # the cap clamps the base term
        assert self._policy(base_delay_s=1.0, max_delay_s=2.0,
                            jitter=0.0).delay_for(6) == 2.0

    def test_deadline_stops_retrying(self):
        t = {"now": 0.0}

        def clock():
            return t["now"]

        def sleep(d):
            t["now"] += d

        def always():
            t["now"] += 1.0
            raise faults.InjectedOOM("s", "injected oom")

        st = RetryStats()
        p = RetryPolicy(max_attempts=100, base_delay_s=0.5,
                        jitter=0.0, deadline_s=2.0, sleep=sleep,
                        clock=clock)
        before = obs_metrics.snapshot()
        with pytest.raises(faults.InjectedOOM):
            p.call(always, stats=st)
        assert st.gave_up_reason == "deadline"
        assert st.deadline_exceeded
        assert st.attempts < 100
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["query_deadline_exceeded_total"] == 1

    def test_success_past_deadline_is_flagged(self):
        t = {"now": 0.0}

        def slow():
            t["now"] += 5.0
            return 42

        st = RetryStats()
        p = RetryPolicy(deadline_s=1.0, clock=lambda: t["now"],
                        sleep=lambda d: None)
        assert p.call(slow, stats=st) == 42
        assert st.deadline_exceeded and st.gave_up_reason is None

    def test_from_config(self):
        cfg = EngineConfig(overrides={
            "engine.retry.max_attempts": "5",
            "engine.retry.base_delay_s": "0.5",
            "engine.retry.max_delay_s": "9",
            "engine.retry.jitter": "0",
            "engine.query_deadline_s": "30",
        })
        p = RetryPolicy.from_config(cfg)
        assert p.max_attempts == 5 and p.base_delay_s == 0.5
        assert p.max_delay_s == 9 and p.deadline_s == 30.0
        # absent/zero deadline means none
        assert RetryPolicy.from_config(EngineConfig()).deadline_s is None

    def test_attempts_iterator_sleeps_between(self):
        slept = []
        p = RetryPolicy(max_attempts=4, base_delay_s=0.1, jitter=0.0,
                        sleep=slept.append)
        assert list(p.attempts()) == [0, 1, 2, 3]
        assert slept == [0.1, 0.2, 0.4]

    def test_with_attempts_preserves_everything_else(self):
        slept = []
        p = RetryPolicy(max_attempts=5, base_delay_s=0.2,
                        max_delay_s=7.0, jitter=0.5, deadline_s=30.0,
                        seed=3, sleep=slept.append)
        q = p.with_attempts(2)
        assert q.max_attempts == 2
        assert (q.base_delay_s, q.max_delay_s, q.jitter, q.deadline_s,
                q.seed) == (0.2, 7.0, 0.5, 30.0, 3)
        assert q._sleep is p._sleep and q._clock is p._clock


# ----------------------------------------------- failure collector

class TestTaskFailureCollector:
    def test_concurrent_notify_and_dedup(self):
        from nds_tpu.utils.report import TaskFailureCollector
        col = TaskFailureCollector()
        col.register()
        try:
            def hammer(i):
                for _ in range(50):
                    TaskFailureCollector.notify("overflow retry")
                TaskFailureCollector.notify(f"unique-{i}")

            threads = [threading.Thread(target=hammer, args=(i,))
                       for i in range(8)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        finally:
            col.unregister()
        # deduplicated: one entry for the repeated reason + 8 uniques
        assert col.failures.count("overflow retry") == 1
        assert len(col.failures) == 9
        fmt = col.formatted()
        assert "overflow retry (x400)" in fmt
        assert "unique-3" in fmt

    def test_report_carries_dedup_counts(self):
        from nds_tpu.utils.report import BenchReport, TaskFailureCollector

        def body():
            for _ in range(3):
                TaskFailureCollector.notify("slack retry")

        rep = BenchReport("q")
        s = rep.report_on(body)
        assert s["queryStatus"] == ["CompletedWithTaskFailures"]
        assert s["exceptions"] == ["slack retry (x3)"]


# ------------------------------------------------------ NDS108 lint

def _lint(src: str, enabled=None):
    return lint_rules.lint_sources({"nds_tpu/x.py": src},
                                   enabled=enabled)


def _rules(violations):
    return {v.rule for v in violations}


class TestNakedRetryRule:
    def test_uncapped_while_true_flags(self):
        src = ("import time\n"
               "def f(op):\n"
               "    while True:\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            time.sleep(1)\n")
        assert _rules(_lint(src, enabled={"NDS108"}).violations) \
            == {"NDS108"}

    def test_constant_sleep_in_capped_loop_flags(self):
        src = ("import time\n"
               "def f(op):\n"
               "    for i in range(5):\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            time.sleep(0.5)\n")
        assert _rules(_lint(src, enabled={"NDS108"}).violations) \
            == {"NDS108"}

    def test_backoff_and_cap_is_clean(self):
        src = ("import time\n"
               "def f(op):\n"
               "    delay = 0.1\n"
               "    for i in range(5):\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            time.sleep(delay)\n"
               "            delay *= 2\n")
        assert _lint(src, enabled={"NDS108"}).violations == []

    def test_loop_without_sleep_is_clean(self):
        src = ("def f(op):\n"
               "    for i in range(3):\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            pass\n")
        assert _lint(src, enabled={"NDS108"}).violations == []

    def test_waiver_applies(self):
        # the standalone waiver covers the next line (the flagged
        # `while True`)
        src = ("import time\n"
               "def f(op):\n"
               "    # ndslint: waive[NDS108] -- external rate limit "
               "mandates a fixed poll interval\n"
               "    while True:\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            time.sleep(1)\n")
        res = _lint(src, enabled={"NDS108"})
        assert res.violations == [] and len(res.waived) == 1

    def test_in_default_rules(self):
        assert any(r.id == "NDS108"
                   for r in lint_rules.default_rules())


# ------------------------------------------------------ phase journal

class TestPhaseJournal:
    def test_round_trip_and_digest_guard(self, tmp_path):
        path = str(tmp_path / "bench_state.json")
        dg = config_digest({"scale": 1})
        j = PhaseJournal(path, dg)
        j.reset()
        j.complete("load_test", load_time_s=5.5, rngseed=99)
        j2 = PhaseJournal(path, dg)
        assert j2.load()
        assert j2.done("load_test") and not j2.done("power_test")
        assert j2.timings("load_test") == {"load_time_s": 5.5,
                                           "rngseed": 99}
        with pytest.raises(JournalMismatch):
            PhaseJournal(path, config_digest({"scale": 2})).load()

    def test_reset_drops_prior_state(self, tmp_path):
        path = str(tmp_path / "bench_state.json")
        j = PhaseJournal(path, "d")
        j.complete("power_test", power_time_s=1.0)
        j.reset()
        j2 = PhaseJournal(path, "d")
        assert not j2.load()

    def test_write_is_atomic(self, tmp_path):
        path = str(tmp_path / "bench_state.json")
        j = PhaseJournal(path, "d")
        j.complete("a", x=1)
        assert not os.path.exists(path + ".tmp")
        assert json.load(open(path))["phases"]["a"]["timings"] == {"x": 1}

    def test_missing_file_loads_empty(self, tmp_path):
        assert not PhaseJournal(str(tmp_path / "nope.json"), "d").load()


# --------------------------------------- power loop integration (cpu)

def _run_stream(mini_wh, tmp_path, overrides=None, subset=None,
                warmup=0):
    from nds_tpu.nds.power import SUITE
    cfg = EngineConfig(overrides={"engine.backend": "cpu",
                                  "engine.retry.base_delay_s": "0.01",
                                  **(overrides or {})})
    jsons = str(tmp_path / "json")
    failures = power_core.run_query_stream(
        SUITE, mini_wh["raw"], mini_wh["stream"],
        str(tmp_path / "time.csv"), config=cfg, input_format="raw",
        json_summary_folder=jsons, query_subset=subset, warmup=warmup)
    summaries = {}
    for f in os.listdir(jsons):
        with open(os.path.join(jsons, f)) as fh:
            s = json.load(fh)
        # failed queries drop flight-recorder dumps (obs/fleet.py)
        # next to their summaries; only BenchReports count here
        if isinstance(s, dict) and "query" in s:
            summaries[s["query"]] = s
    return failures, summaries


class TestPowerLoopResilience:
    def test_transient_oom_retried_to_completion(self, mini_wh,
                                                 tmp_path):
        faults.install("device.execute:oom@query7")
        failures, sums = _run_stream(mini_wh, tmp_path)
        assert failures == 0
        assert sums["query7"]["queryStatus"] == ["Completed"]
        assert sums["query7"]["retries"] == 1
        assert sums["query7"]["retry_backoff_s"] > 0
        assert sums["query96"]["retries"] == 0

    def test_transient_plan_fault_retried_to_completion(self, mini_wh,
                                                        tmp_path):
        """A TRANSIENT failure in the parse/plan window (before the
        pipeline's executor dispatch) still retries under the config
        policy — the power loop's front-door retry covers the window
        the scheduler cannot see."""
        faults.install("plan:fault*1@query96")
        failures, sums = _run_stream(mini_wh, tmp_path)
        assert failures == 0
        assert sums["query96"]["queryStatus"] == ["Completed"]
        assert sums["query96"]["retries"] == 1
        assert sums["query96"]["retry_backoff_s"] > 0

    def test_plan_window_retry_honors_deadline(self, mini_wh,
                                               tmp_path):
        """The front-door retry enforces engine.query_deadline_s like
        the executor-phase policy: a backoff that would overrun the
        budget gives up with gave_up_reason=deadline instead of
        sleeping past it."""
        faults.install("plan:fault*99@query96")
        failures, sums = _run_stream(
            mini_wh, tmp_path,
            overrides={"engine.query_deadline_s": "0.05",
                       "engine.retry.base_delay_s": "30"},
            subset=["query96"])
        assert failures == 1
        s = sums["query96"]
        assert s["queryStatus"] == ["Failed"]
        assert s["gave_up_reason"] == "deadline"
        assert s["deadline_exceeded"] is True

    def test_plan_fault_fails_fast(self, mini_wh, tmp_path):
        faults.install("plan:deterministic@query96")
        failures, sums = _run_stream(mini_wh, tmp_path)
        assert failures == 1
        s = sums["query96"]
        assert s["queryStatus"] == ["Failed"]
        assert s["retries"] == 0
        assert s["gave_up_reason"] == "deterministic"
        assert any("injected deterministic" in e
                   for e in s["exceptions"])
        # the stream kept going past the failure
        assert sums["query7"]["queryStatus"] == ["Completed"]

    def test_plan_fault_fires_despite_warmup_plan_cache(self, mini_wh,
                                                        tmp_path):
        """The suppressed warmup pass plans and CACHES the query; the
        timed pass takes the plan-cache hit — the plan chaos site must
        still fire there (Session fires it on cache hits too)."""
        faults.install("plan:deterministic@query96")
        failures, sums = _run_stream(mini_wh, tmp_path,
                                     subset=["query96"], warmup=1)
        assert failures == 1
        assert sums["query96"]["queryStatus"] == ["Failed"]
        assert sums["query96"]["gave_up_reason"] == "deterministic"

    def test_query_deadline_flagged(self, mini_wh, tmp_path):
        before = obs_metrics.snapshot()
        failures, sums = _run_stream(
            mini_wh, tmp_path,
            overrides={"engine.query_deadline_s": "0.000001"},
            subset=["query96"])
        assert failures == 0
        assert sums["query96"]["deadline_exceeded"] is True
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["query_deadline_exceeded_total"] >= 1

    def test_sticky_demotion_after_repeated_ladder_exhaustion(
            self, mini_wh, tmp_path):
        # tpu backend on the virtual-CPU mesh: the first two queries
        # exhaust the WHOLE ladder on injected OOM (the query-scoped
        # fault fires at every placement, floor included), the
        # reschedule streak sticky-demotes the stream's STARTING rung
        # to the floor, and the LAST query runs directly on the CPU
        # oracle — the old one-shot engine.fallback=cpu contract,
        # now expressed as a (reversible) scheduling decision
        faults.install("device.execute:oom*99@query96,"
                       "device.execute:oom*99@query7")
        before = obs_metrics.snapshot()
        failures, sums = _run_stream(
            mini_wh, tmp_path,
            overrides={"engine.backend": "tpu",
                       "engine.fallback": "cpu"})
        assert failures == 2
        assert sums["query96"]["gave_up_reason"].startswith(
            "attempts_exhausted")
        assert sums["query7"]["gave_up_reason"].startswith(
            "attempts_exhausted")
        # the failed queries record their ladder walk
        assert sums["query96"]["ladder"] == ["device", "chunked", "cpu"]
        assert sums["query96"]["reschedules"] == 2
        assert sums["query93"]["queryStatus"] == ["Completed"]
        # demoted start: query93 began at the floor, no ladder walk
        assert sums["query93"]["placement"] == "cpu"
        assert sums["query93"]["reschedules"] == 0
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["placement_demotions_total"] == 1
        assert d["counters"]["query_reschedules_total"] >= 4

    def test_allow_failure_exit_code_contract(self, mini_wh, tmp_path,
                                              monkeypatch):
        """--allow_failure end-to-end through the driver main: one
        injected deterministic failure exits 1 without the flag, 0
        with it, and the TimeLog CSV carries every query either way."""
        from nds_tpu.nds.power import main
        from nds_tpu.utils.timelog import TimeLog
        monkeypatch.setenv(faults.FAULTS_ENV,
                           "plan:deterministic@query96")
        faults.clear()  # drop any cached env plan

        def drive(tag, *extra):
            tlog = str(tmp_path / f"{tag}.csv")
            jsons = str(tmp_path / f"json_{tag}")
            with pytest.raises(SystemExit) as ei:
                main([mini_wh["raw"], mini_wh["stream"], tlog,
                      "--backend", "cpu", "--input_format", "raw",
                      "--json_summary_folder", jsons, *extra])
            names = [q for _a, q, _ms in TimeLog.read(tlog)]
            failed = 0
            for f in os.listdir(jsons):
                with open(os.path.join(jsons, f)) as fh:
                    s = json.load(fh)
                # flight-recorder dumps land next to the summaries
                if s.get("queryStatus") == ["Failed"]:
                    failed += 1
            return ei.value.code, names, failed

        faults.clear()
        code, names, failed = drive("strict")
        assert code == 1 and failed == 1
        assert {"query96", "query7", "query93"} <= set(names)
        faults.clear()  # fresh budget for the second run
        code, names, failed = drive("lenient", "--allow_failure")
        assert code == 0 and failed == 1
        assert {"query96", "query7", "query93"} <= set(names)


# ------------------------------------------- chunked OOM degradation

def _chunked_session(mini_wh, chunk_rows):
    from nds_tpu.engine.chunked_exec import make_chunked_factory
    from nds_tpu.engine.session import Session
    from nds_tpu.io import csv_io
    from nds_tpu.nds.schema import get_schemas

    schema = get_schemas()["store_sales"]
    paths = [os.path.join(mini_wh["raw"], "store_sales", f)
             for f in sorted(os.listdir(
                 os.path.join(mini_wh["raw"], "store_sales")))]
    table = csv_io.read_tbl(paths, "store_sales", schema)
    sess = Session.for_nds(
        make_chunked_factory(stream_bytes=1, chunk_rows=chunk_rows))
    sess.register_table(table)
    return sess, table


def test_chunked_executor_halves_chunks_on_oom(mini_wh):
    sess, table = _chunked_session(mini_wh, chunk_rows=1 << 14)
    before = obs_metrics.snapshot()
    faults.install("device.execute:oom*2@*")
    res = sess.sql("select count(*) c from store_sales").to_pandas()
    assert int(res["c"][0]) == table.nrows
    ex = sess._executor_factory(sess.tables)
    # two OOMs -> two halvings before the third attempt succeeded
    assert ex.chunk_rows == 1 << 12
    d = obs_metrics.delta(before, obs_metrics.snapshot())
    assert d["counters"]["chunk_shrink_total"] == 2


def test_chunked_oom_at_floor_falls_back_to_full_upload(mini_wh):
    """With chunk_rows already at the halving floor, a partial-agg OOM
    must fall back to the full-upload phase B (the pre-resilience
    behavior), not fail the query."""
    sess, table = _chunked_session(mini_wh, chunk_rows=1 << 12)
    before = obs_metrics.snapshot()
    faults.install("device.execute:oom@*")
    res = sess.sql("select count(*) c from store_sales").to_pandas()
    assert int(res["c"][0]) == table.nrows
    ex = sess._executor_factory(sess.tables)
    assert ex.chunk_rows == 1 << 12     # no halving happened
    d = obs_metrics.delta(before, obs_metrics.snapshot())
    assert "chunk_shrink_total" not in d.get("counters", {})


# --------------------------------------- throughput stream reports

class TestThroughputResilience:
    @pytest.fixture(scope="class")
    def tstreams(self, mini_wh, tmp_path_factory):
        sdir = str(tmp_path_factory.mktemp("tstreams"))
        return streams.generate_query_streams(
            sdir, 2, rng_seed=7, templates=[96, 7],
            qualification=False)

    def _reports(self, out):
        reps = {}
        for f in os.listdir(out):
            if f.endswith(".json"):
                with open(os.path.join(out, f)) as fh:
                    s = json.load(fh)
                if isinstance(s, dict) and "query" in s:
                    reps[s["query"]] = s
        return reps

    def test_clean_run_writes_stream_reports(self, mini_wh, tstreams,
                                             tmp_path):
        from nds_tpu.nds.throughput import run_streams_inprocess
        out = str(tmp_path / "tp")
        elapse, fails = run_streams_inprocess(
            mini_wh["raw"], tstreams, out, backend="cpu",
            input_format="raw")
        assert fails == [0, 0]
        reps = self._reports(out)
        assert set(reps) == {"query_0", "query_1"}
        for r in reps.values():
            assert r["queryStatus"] == ["Completed"] * 2
            assert r["exceptions"] == [] and r["retries"] == 0

    def test_transient_fault_retried_in_stream(self, mini_wh,
                                               tstreams, tmp_path):
        from nds_tpu.nds.throughput import run_streams_inprocess
        faults.install("device.execute:oom@query7")
        out = str(tmp_path / "tp")
        _elapse, fails = run_streams_inprocess(
            mini_wh["raw"], tstreams, out, backend="cpu",
            input_format="raw")
        assert fails == [0, 0]
        reps = self._reports(out)
        assert sum(r["retries"] for r in reps.values()) == 1
        for r in reps.values():
            assert r["queryStatus"] == ["Completed"] * 2

    def test_failure_text_lands_in_stream_report(self, mini_wh,
                                                 tstreams, tmp_path):
        from nds_tpu.nds.throughput import run_streams_inprocess
        faults.install("plan:deterministic@query96")
        out = str(tmp_path / "tp")
        _elapse, fails = run_streams_inprocess(
            mini_wh["raw"], tstreams, out, backend="cpu",
            input_format="raw")
        assert sum(fails) == 1
        reps = self._reports(out)
        failed = [r for r in reps.values() if "Failed" in
                  r["queryStatus"]]
        assert len(failed) == 1
        assert any("injected deterministic" in e
                   for e in failed[0]["exceptions"])


# --------------------------------------------------- resumable bench

class TestBenchResume:
    @staticmethod
    def _fake_phases(monkeypatch, calls):
        """Replace every subprocess phase with a recorder that writes
        the artifact the orchestrator reads back."""
        from nds_tpu.nds import bench as bench_mod
        from nds_tpu.utils.timelog import TimeLog

        def fake_run(cmd, backend=None, extra_env=None):
            calls.append(cmd[2])
            mod = cmd[2]
            if mod == "nds_tpu.nds.transcode":
                with open(cmd[5], "w") as f:
                    f.write("Total conversion time for 24 tables was "
                            "5.0s\nRNGSEED used: 123\n")
            elif mod == "nds_tpu.nds.power":
                t = TimeLog("fake")
                t.add("Power Test Time", 2000)
                t.write(cmd[5])
            elif mod == "nds_tpu.nds.maintenance":
                t = TimeLog("fake")
                t.add("Data Maintenance Time", 1500)
                t.write(cmd[5])

        def fake_streams(*a, **kw):
            calls.append("stream_gen")

        def fake_tp(*a, **kw):
            calls.append("throughput")
            return 3.0, [0]

        def fake_run_rc(cmd, backend=None, extra_env=None):
            # the power phase goes through the resumable-exit wrapper
            fake_run(cmd, backend, extra_env)
            return 0

        monkeypatch.setattr(bench_mod, "_run", fake_run)
        monkeypatch.setattr(bench_mod, "_run_rc", fake_run_rc)
        import nds_tpu.nds.streams as streams_mod
        import nds_tpu.nds.throughput as tp_mod
        monkeypatch.setattr(streams_mod, "generate_query_streams",
                            fake_streams)
        monkeypatch.setattr(tp_mod, "run_streams", fake_tp)
        monkeypatch.setattr(tp_mod, "run_streams_inprocess", fake_tp)

    def _cfg(self, tmp_path):
        work = tmp_path / "w"
        return {
            "scale_factor": 0.01, "parallel": 2, "num_streams": 1,
            "backend": "cpu",
            "paths": {
                "raw_data": str(work / "raw"),
                "warehouse": str(work / "wh"),
                "streams": str(work / "streams"),
                "reports": str(work / "reports"),
            },
            "skip": {},
        }

    def test_resume_skips_completed_phases(self, tmp_path,
                                           monkeypatch):
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        m1 = run_full_bench(cfg)
        assert m1["metric"] is not None and m1["metric"] > 0
        assert calls  # everything ran
        state = json.load(open(os.path.join(cfg["paths"]["reports"],
                                            "bench_state.json")))
        assert set(state["phases"]) == {
            "data_gen", "load_test", "stream_gen", "power_test",
            "throughput_1", "maintenance_1", "throughput_2",
            "maintenance_2"}
        # resumed run: NOTHING re-executes, identical metric
        calls.clear()
        m2 = run_full_bench(cfg, resume=True)
        assert calls == []
        assert m2["metric"] == m1["metric"]

    def test_resume_after_crash_reruns_only_the_tail(self, tmp_path,
                                                     monkeypatch):
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        m1 = run_full_bench(cfg)
        # simulate a crash during throughput round 2: drop it and
        # everything after from the journal
        jpath = os.path.join(cfg["paths"]["reports"],
                             "bench_state.json")
        state = json.load(open(jpath))
        for ph in ("throughput_2", "maintenance_2"):
            del state["phases"][ph]
        # hand-edited journal: drop the stale CRC stamp (an unstamped
        # journal is trusted legacy; a MISmatched one is torn — that
        # path is covered by test_crc_tampered_journal_also_degrades)
        state.pop("crc", None)
        with open(jpath, "w") as f:
            json.dump(state, f)
        calls.clear()
        m2 = run_full_bench(cfg, resume=True)
        # load+power replayed from the journal (no transcode/power
        # subprocess), only the crashed tail re-ran
        assert "nds_tpu.nds.transcode" not in calls
        assert "nds_tpu.nds.power" not in calls
        assert calls.count("throughput") == 1
        assert calls.count("nds_tpu.nds.maintenance") == 1
        assert m2["metric"] == m1["metric"]

    def test_resume_refuses_config_drift(self, tmp_path, monkeypatch):
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        run_full_bench(cfg)
        cfg2 = dict(cfg)
        cfg2["scale_factor"] = 3000
        with pytest.raises(JournalMismatch):
            run_full_bench(cfg2, resume=True)

    def test_fresh_run_resets_stale_journal(self, tmp_path,
                                            monkeypatch):
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        run_full_bench(cfg)
        n = len(calls)
        calls.clear()
        run_full_bench(cfg)  # NOT resume: everything re-runs
        assert len(calls) == n

    def test_torn_journal_resumes_fresh_with_warning(self, tmp_path,
                                                     monkeypatch,
                                                     capsys):
        """Truncated bench_state.json: --resume warns, re-runs every
        phase, and computes the SAME final metric a clean run would —
        never a crash, never a splice of half-recorded phases."""
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        m1 = run_full_bench(cfg)
        n_phases = len(calls)
        jpath = os.path.join(cfg["paths"]["reports"],
                             "bench_state.json")
        blob = open(jpath).read()
        with open(jpath, "w") as f:
            f.write(blob[: len(blob) // 2])  # torn mid-write
        calls.clear()
        m2 = run_full_bench(cfg, resume=True)
        out = capsys.readouterr().out
        assert "torn/corrupt" in out
        assert len(calls) == n_phases   # nothing replayed from the wreck
        assert m2["metric"] == m1["metric"]

    def test_crc_tampered_journal_also_degrades(self, tmp_path,
                                                monkeypatch, capsys):
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        run_full_bench(cfg)
        jpath = os.path.join(cfg["paths"]["reports"],
                             "bench_state.json")
        state = json.load(open(jpath))
        assert "crc" in state
        state["phases"]["power_test"]["timings"]["power_time_s"] = 9e9
        with open(jpath, "w") as f:
            json.dump(state, f)     # valid JSON, stale CRC
        calls.clear()
        run_full_bench(cfg, resume=True)
        assert "torn/corrupt" in capsys.readouterr().out
        assert calls                # phases re-ran, tamper not trusted


# ------------------------------------------------ heartbeat watchdog

@pytest.fixture(autouse=True)
def _clean_heartbeats():
    yield
    watchdog.reset()


class TestWatchdog:
    def test_beat_registry_snapshot_and_clear(self):
        watchdog.beat("u1", query="query5", phase="dispatch", attempt=2)
        e = watchdog.snapshot_heartbeats()["u1"]
        assert e["query"] == "query5" and e["phase"] == "dispatch"
        assert e["attempt"] == 2 and e["count"] == 1
        assert e["age_s"] >= 0
        watchdog.beat("u1", query="query6")
        assert watchdog.snapshot_heartbeats()["u1"]["count"] == 2
        watchdog.clear_unit("u1")
        assert watchdog.snapshot_heartbeats() == {}

    def test_stall_report_schema_counter_and_rearm(self, tmp_path):
        wd = watchdog.Watchdog(stall_s=0.01, run_dir=str(tmp_path))
        before = obs_metrics.snapshot()
        watchdog.beat("stream", query="query5", phase="dispatch")
        time.sleep(0.03)
        path = wd.check_once()
        assert path and os.path.basename(path) == "stall-query5.json"
        rep = json.load(open(path))
        for key in ("unit", "query", "phase", "attempt", "age_s",
                    "stall_s", "action", "ts", "pid", "heartbeats",
                    "threads", "metrics"):
            assert key in rep, key
        assert rep["unit"] == "stream" and rep["query"] == "query5"
        assert rep["age_s"] > rep["stall_s"] == 0.01
        # this test thread's stack is in the dump
        assert any("test_stall_report" in line
                   for frames in rep["threads"].values()
                   for line in frames)
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["watchdog_stalls_total"] == 1
        # the SAME silence reports once...
        assert wd.check_once() is None
        # ...a new beat re-arms, and the next report gets a -2 suffix
        watchdog.beat("stream", query="query5", phase="retry")
        time.sleep(0.03)
        p2 = wd.check_once()
        assert p2 and p2.endswith("stall-query5-2.json")

    def test_any_units_beat_keeps_the_alarm_armed(self, tmp_path):
        """Progress ANYWHERE re-arms: a slow query whose chunk loop
        still beats must never read as a stall."""
        wd = watchdog.Watchdog(stall_s=0.05, run_dir=str(tmp_path))
        watchdog.beat("stream", query="query5")
        time.sleep(0.07)
        watchdog.beat("engine", phase="chunk.scan")
        assert wd.check_once() is None

    def test_kill_action_dumps_then_exits(self, tmp_path):
        codes = []
        wd = watchdog.Watchdog(stall_s=0.01, action="kill",
                               run_dir=str(tmp_path),
                               _exit=codes.append)
        watchdog.beat("s", query="query1")
        time.sleep(0.03)
        wd.check_once()
        assert codes == [watchdog.EXIT_STALLED]
        assert os.path.exists(os.path.join(str(tmp_path),
                                           "stall-query1.json"))

    def test_from_config_and_env(self, monkeypatch, tmp_path):
        cfg = EngineConfig(overrides={"engine.watchdog.stall_s": "5",
                                      "engine.watchdog.action": "kill"})
        wd = watchdog.Watchdog.from_config(cfg, str(tmp_path))
        assert wd.stall_s == 5.0 and wd.action == "kill"
        assert watchdog.Watchdog.from_config(EngineConfig(), ".") is None
        monkeypatch.setenv(watchdog.WATCHDOG_ENV, "2.5:report")
        wd2 = watchdog.Watchdog.from_env(".")
        assert wd2.stall_s == 2.5 and wd2.action == "report"
        monkeypatch.delenv(watchdog.WATCHDOG_ENV)
        assert watchdog.Watchdog.from_env(".") is None
        with pytest.raises(ValueError):
            watchdog.Watchdog(stall_s=1.0, action="bogus")

    def test_snapshot_embeds_heartbeats(self, tmp_path):
        from nds_tpu.obs.snapshot import MetricsSnapshotter
        watchdog.beat("stream", query="query9", phase="dispatch")
        path = str(tmp_path / "snap.json")
        MetricsSnapshotter(path).write_once()
        doc = json.load(open(path))
        assert doc["heartbeats"]["stream"]["query"] == "query9"
        assert doc["heartbeats"]["stream"]["age_s"] >= 0


# -------------------------------------------- hang & corrupt kinds

class TestHangCorruptKinds:
    def test_parse_defaults(self):
        specs = faults.parse_schedule(
            "stream.query:hang=30@query_1,io.read:corrupt@store_*")
        assert specs[0].kind == "hang" and specs[0].param == 30.0
        assert specs[0].times == 1      # hang once, like raising kinds
        assert specs[1].kind == "corrupt" and specs[1].times == 1

    def test_hang_sleeps_param_seconds(self):
        faults.install("plan:hang=0.1@*")
        t0 = time.monotonic()
        faults.fault_point("plan")
        assert time.monotonic() - t0 >= 0.1
        faults.fault_point("plan")      # budget spent: instant no-op
        assert time.monotonic() - t0 < 5

    def test_hang_is_interruptible(self):
        faults.install("plan:hang=60@*")
        t = threading.Thread(target=faults.fault_point, args=("plan",))
        t.start()
        time.sleep(0.1)
        assert t.is_alive()             # genuinely hung
        faults.interrupt_hangs()
        t.join(timeout=2)
        assert not t.is_alive()

    def test_corrupt_flips_one_byte_once(self, tmp_path):
        p = str(tmp_path / "chunk.dat")
        with open(p, "wb") as f:
            f.write(b"0123456789")
        faults.install("io.read:corrupt@*")
        faults.fault_point("io.read", table="t", paths=[p])
        mutated = open(p, "rb").read()
        assert mutated != b"0123456789"
        assert len(mutated) == 10       # flip, not truncate
        faults.fault_point("io.read", table="t", paths=[p])
        assert open(p, "rb").read() == mutated  # times=1: fired once

    def test_corrupt_without_paths_context_raises(self):
        faults.install("io.read:corrupt@*")
        with pytest.raises(ValueError, match="paths"):
            faults.fault_point("io.read", table="t")


# ------------------------------------------------ artifact integrity

class TestIntegrity:
    def test_manifest_roundtrip_then_mismatch(self, tmp_path):
        d = str(tmp_path / "tbl")
        os.makedirs(d)
        p = os.path.join(d, "part-0.parquet")
        with open(p, "wb") as f:
            f.write(b"payload-bytes")
        integrity.write_manifest(d)
        integrity.set_verify(True)
        try:
            integrity.verify_paths([p], "tbl")  # clean: no raise
            with open(p, "r+b") as f:
                f.seek(4)
                f.write(b"X")
            integrity.clear_cache()
            with pytest.raises(integrity.CorruptArtifact) as ei:
                integrity.verify_paths([p], "tbl")
            msg = str(ei.value)
            assert p in msg and "sha256 expected" in msg
            assert ei.value.expected != ei.value.actual
        finally:
            integrity.set_verify(None)

    def test_corrupt_artifact_is_deterministic(self):
        assert classify(integrity.CorruptArtifact("f", "a", "b")) \
            == DETERMINISTIC

    def test_unmanifested_files_load_unverified(self, tmp_path):
        p = str(tmp_path / "legacy.dat")
        with open(p, "wb") as f:
            f.write(b"no manifest anywhere")
        integrity.set_verify(True)
        try:
            integrity.verify_paths([p], "legacy")   # no raise
        finally:
            integrity.set_verify(None)

    def test_disabled_gate_skips_hashing(self, tmp_path):
        d = str(tmp_path / "tbl")
        os.makedirs(d)
        p = os.path.join(d, "f.bin")
        with open(p, "wb") as f:
            f.write(b"abc")
        integrity.write_manifest(d)
        with open(p, "wb") as f:
            f.write(b"xyz")
        integrity.set_verify(False)
        try:
            integrity.verify_paths([p], "tbl")      # gate off: no raise
        finally:
            integrity.set_verify(None)

    def test_read_tbl_verifies_digests(self, tmp_path):
        from nds_tpu.engine.types import INT64, Schema
        from nds_tpu.io import csv_io
        d = str(tmp_path / "t")
        os.makedirs(d)
        p = os.path.join(d, "t_1_1.dat")
        with open(p, "w") as f:
            f.write("1|2|\n3|4|\n")
        integrity.write_manifest(d)
        schema = Schema.of(("a", INT64, False), ("b", INT64, False))
        integrity.set_verify(True)
        try:
            t = csv_io.read_tbl([p], "t", schema)
            assert t.nrows == 2
            with open(p, "r+b") as f:
                f.seek(2)
                f.write(b"9")
            integrity.clear_cache()
            with pytest.raises(integrity.CorruptArtifact):
                csv_io.read_tbl([p], "t", schema)
        finally:
            integrity.set_verify(None)

    def test_crc_stamp_and_check(self):
        doc = integrity.stamp_crc({"a": 1, "b": [2, 3]})
        assert integrity.check_crc(doc)
        tampered = {**doc, "a": 2}
        assert not integrity.check_crc(tampered)
        assert integrity.check_crc({"legacy": "no-crc"})

    def test_write_json_atomic_leaves_no_tmp(self, tmp_path):
        p = str(tmp_path / "x" / "doc.json")
        integrity.write_json_atomic(p, {"k": 1})
        assert json.load(open(p)) == {"k": 1}
        assert os.listdir(os.path.dirname(p)) == ["doc.json"]

    def test_torn_snapshot_manifest_degrades_to_baseline(self,
                                                         tmp_path,
                                                         capsys):
        from nds_tpu.io.snapshots import MANIFEST, SnapshotLog
        wh = str(tmp_path / "wh")
        os.makedirs(os.path.join(wh, "t1"))
        log = SnapshotLog(wh)
        log.commit({"t1": ["t1/_v1/part-0.parquet"]}, note="m1")
        assert SnapshotLog(wh).entries      # round-trips
        mpath = os.path.join(wh, MANIFEST)
        blob = open(mpath).read()
        with open(mpath, "w") as f:
            f.write(blob[: len(blob) // 2])
        log2 = SnapshotLog(wh)
        assert log2.entries == []           # baseline, not a crash
        assert "torn/corrupt" in capsys.readouterr().out


# --------------------------------------------- mid-attempt deadlines

class TestMidAttemptDeadline:
    def _clocked(self, **kw):
        t = {"now": 0.0}
        calls = []
        pol = RetryPolicy(base_delay_s=0.1, jitter=0.0,
                          clock=lambda: t["now"],
                          sleep=calls.append, **kw)
        return pol, t, calls

    def test_check_deadline_scope(self):
        t = {"now": 0.0}
        check_deadline()                    # outside any scope: no-op
        with deadline_scope(1.0, clock=lambda: t["now"]):
            check_deadline()                # within budget
            t["now"] = 2.0
            with pytest.raises(QueryDeadlineExceeded):
                check_deadline()
        check_deadline()                    # scope popped

    def test_policy_publishes_scope_and_flags_abort(self):
        pol, t, _ = self._clocked(deadline_s=1.0, max_attempts=3)
        st = RetryStats()

        def body():
            t["now"] = 5.0                  # attempt overruns mid-flight
            check_deadline()

        with pytest.raises(QueryDeadlineExceeded):
            pol.call(body, stats=st)
        assert st.attempts == 1             # never retried
        assert st.gave_up_reason == "deadline"
        assert st.deadline_exceeded is True

    def test_deadline_recorded_when_final_attempt_raises(self):
        pol, t, _ = self._clocked(deadline_s=10.0, max_attempts=2)
        st = RetryStats()

        def body():
            t["now"] += 6.0                 # 2 attempts -> t=12 > 10
            raise RuntimeError("RESOURCE_EXHAUSTED: fake")

        with pytest.raises(RuntimeError):
            pol.call(body, stats=st)
        assert st.gave_up_reason == "attempts_exhausted(2)"
        assert st.deadline_exceeded is True  # overrun recorded too

    def test_deterministic_failure_past_deadline_flags(self):
        pol, t, _ = self._clocked(deadline_s=1.0, max_attempts=3)
        st = RetryStats()

        def body():
            t["now"] = 9.0
            raise ValueError("planner bug")

        with pytest.raises(ValueError):
            pol.call(body, stats=st)
        assert st.gave_up_reason == DETERMINISTIC
        assert st.deadline_exceeded is True

    def test_within_deadline_keeps_flags_clear(self):
        pol, t, _ = self._clocked(deadline_s=10.0, max_attempts=2)
        st = RetryStats()
        assert pol.call(lambda: 42, stats=st) == 42
        assert st.deadline_exceeded is False


def test_chunked_execution_honors_deadline_between_chunks(mini_wh):
    """An already-expired deadline stops a streamed query at the next
    chunk boundary — inside the attempt, not after it."""
    sess, _table = _chunked_session(mini_wh, chunk_rows=1 << 12)
    t = {"now": 0.0}
    with deadline_scope(1.0, clock=lambda: t["now"]):
        t["now"] = 5.0
        with pytest.raises(QueryDeadlineExceeded):
            sess.sql("select count(*) c from store_sales")


# ------------------------------------------------- stream supervisor

def _script_spec(name, out_dir, scripts, hb_path=None, queries=()):
    """StreamSpec whose incarnations run the given -c scripts (the
    last script repeats once the list is exhausted)."""
    def make_cmd(incarnation, remaining):
        body = scripts[min(incarnation, len(scripts) - 1)]
        return [sys.executable, "-c", body]
    return supervise.StreamSpec(
        name=name, make_cmd=make_cmd,
        hb_path=hb_path or os.path.join(out_dir, f"{name}_hb.json"),
        queries=list(queries))


class TestStreamSupervisor:
    def test_restart_once_then_success(self, tmp_path):
        out = str(tmp_path)
        before = obs_metrics.snapshot()
        spec = _script_spec("s1", out, ["raise SystemExit(3)", "pass"])
        sup = supervise.StreamSupervisor([spec], out, poll_s=0.05)
        _elapse, codes, summary = sup.run()
        s = summary["streams"]["s1"]
        assert codes == [0]
        assert s["exit_codes"] == [3, 0]
        assert s["restarts"] == 1 and s["degraded"]
        assert s["final_code"] == 0
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["stream_restarts_total"] == 1
        # summary artifact on disk
        ondisk = json.load(open(os.path.join(
            out, supervise.SUMMARY_NAME)))
        assert ondisk["streams"]["s1"]["restarts"] == 1

    def test_restart_budget_is_one(self, tmp_path):
        out = str(tmp_path)
        spec = _script_spec("s1", out, ["raise SystemExit(3)"])
        sup = supervise.StreamSupervisor([spec], out, poll_s=0.05)
        _elapse, codes, summary = sup.run()
        s = summary["streams"]["s1"]
        assert codes == [3]
        assert s["exit_codes"] == [3, 3]    # exactly one restart
        assert s["restarts"] == 1 and s["final_code"] == 3

    def test_finished_stream_never_restarts(self, tmp_path):
        """Exit 1 with every query completed is the reference's
        completed-with-failures contract — restarting would re-run
        finished work."""
        out = str(tmp_path)
        hb = os.path.join(out, "s1_hb.json")
        script = (
            "import json\n"
            f"json.dump({{'progress': {{'queries_completed': 2, "
            f"'queries_total': 2}}}}, open(r'{hb}', 'w'))\n"
            "raise SystemExit(1)\n")
        spec = _script_spec("s1", out, [script], hb_path=hb,
                            queries=["query1", "query2"])
        sup = supervise.StreamSupervisor([spec], out, poll_s=0.05)
        _elapse, codes, summary = sup.run()
        s = summary["streams"]["s1"]
        assert s["restarts"] == 0
        assert s["exit_codes"] == [1] and codes == [1]
        assert s["completed"] == 2

    def test_stalled_stream_killed_and_restarted(self, tmp_path):
        """A wedged child (stale heartbeat ages, then silence) is
        SIGTERMed by the parent backstop and restarted once."""
        out = str(tmp_path)
        hb = os.path.join(out, "s1_hb.json")
        hang = (
            "import json, time\n"
            f"json.dump({{'progress': {{}}, 'heartbeats': "
            f"{{'u': {{'age_s': 999, 'count': 1}}}}}}, "
            f"open(r'{hb}', 'w'))\n"
            "time.sleep(60)\n")
        spec = _script_spec("s1", out, [hang, "pass"], hb_path=hb)
        sup = supervise.StreamSupervisor([spec], out, stall_s=0.2,
                                         poll_s=0.05, grace_s=1.0,
                                         startup_grace_s=10.0)
        t0 = time.monotonic()
        _elapse, codes, summary = sup.run()
        assert time.monotonic() - t0 < 30   # never waited the 60 s out
        s = summary["streams"]["s1"]
        assert codes == [0]
        assert s["restarts"] == 1 and s["stalls"]
        assert s["signals"] and s["signals"][0] in (15, 9)
        assert s["stalls"][0]["source"] == "supervisor"
        # supervisor-side stall artifact
        assert os.path.exists(os.path.join(out, "stall-s1.json"))

    def test_resume_never_splits_a_part_group(self):
        """NDS-H q15's parts share in-process state (CREATE VIEW /
        SELECT / DROP VIEW): a restart boundary inside the group must
        snap back to part1, or part2 fails on the missing view."""
        qs = ["query14_part1", "query14_part2", "query15_part1",
              "query15_part2", "query15_part3", "query16"]
        assert supervise.resume_index(qs, 0) == 0
        assert supervise.resume_index(qs, 2) == 2   # group boundary
        assert supervise.resume_index(qs, 3) == 2   # mid-q15: snap back
        assert supervise.resume_index(qs, 4) == 2
        assert supervise.resume_index(qs, 5) == 5   # clean boundary
        assert supervise.resume_index(qs, 6) == 6   # finished
        # mid-q14 snaps to q14's own part1, not further
        assert supervise.resume_index(qs, 1) == 0

    def test_mini_journal_written(self, tmp_path):
        out = str(tmp_path)
        spec = _script_spec("s1", out, ["import time; time.sleep(0.3)"],
                            queries=["q1"])
        sup = supervise.StreamSupervisor([spec], out, poll_s=0.05)
        sup.run()
        j = json.load(open(os.path.join(out, "s1_journal.json")))
        assert j["incarnation"] == 0 and j["restarts"] == 0
        assert j["queries_total"] == 1


# --------------------------------------------------- NDS109 lint

class TestNonAtomicJsonWriteRule:
    def test_bare_dump_flags(self):
        res = _lint(
            "import json\n"
            "def save(path, doc):\n"
            "    with open(path, 'w') as f:\n"
            "        json.dump(doc, f)\n",
            enabled={"NDS109"})
        assert _rules(res.violations) == {"NDS109"}

    def test_tmp_plus_replace_is_clean(self):
        res = _lint(
            "import json, os\n"
            "def save(path, doc):\n"
            "    with open(path + '.tmp', 'w') as f:\n"
            "        json.dump(doc, f)\n"
            "    os.replace(path + '.tmp', path)\n",
            enabled={"NDS109"})
        assert res.violations == []

    def test_fp_keyword_also_flags(self):
        res = _lint(
            "import json\n"
            "def save(path, doc):\n"
            "    with open(path, mode='w') as f:\n"
            "        json.dump(doc, fp=f)\n",
            enabled={"NDS109"})
        assert _rules(res.violations) == {"NDS109"}

    def test_read_handle_is_clean(self):
        res = _lint(
            "import json\n"
            "def load(path):\n"
            "    with open(path) as f:\n"
            "        return json.load(f)\n",
            enabled={"NDS109"})
        assert res.violations == []

    def test_waiver_applies(self):
        res = _lint(
            "import json\n"
            "def save(path, doc):\n"
            "    with open(path, 'w') as f:\n"
            "        # ndslint: waive[NDS109] -- unique path per write\n"
            "        json.dump(doc, f)\n",
            enabled={"NDS109"})
        assert res.violations == [] and len(res.waived) == 1

    def test_in_default_rules(self):
        assert "NDS109" in {r.id for r in lint_rules.default_rules()}


# ------------------------------------------------- query journal

class TestQueryJournal:
    def _j(self, tmp_path, digest="d"):
        from nds_tpu.resilience.journal import QueryJournal
        return QueryJournal(str(tmp_path / "q.json"), phase="power-nds",
                            digest=digest)

    def test_round_trip_starts_and_completions(self, tmp_path):
        j = self._j(tmp_path)
        j.reset()
        j.start("query96")
        j.record("query96", 120.5, "Completed", "cafe")
        j.start("query7")   # started, never finished (the kill window)
        j2 = self._j(tmp_path)
        assert j2.load()
        assert j2.done("query96") and not j2.done("query7")
        e = j2.entry("query96")
        assert e["wall_ms"] == 120.5 and e["status"] == "Completed"
        assert e["result_digest"] == "cafe" and e["incarnation"] == 0
        assert j2.starts("query7") == [0]
        assert sorted(j2.completed()) == ["query96"]

    def test_incarnation_stamps_later_executions(self, tmp_path):
        j = self._j(tmp_path)
        j.reset()
        j.start("q1")
        j.record("q1", 1.0, "Completed")
        j2 = self._j(tmp_path)
        assert j2.load()
        assert j2.begin_incarnation() == 1
        j2.start("q2")
        j2.record("q2", 2.0, "Completed")
        assert j2.entry("q2")["incarnation"] == 1
        assert j2.starts("q2") == [1]
        assert j2.entry("q1")["incarnation"] == 0  # untouched

    def test_torn_journal_counts_reset_and_degrades(self, tmp_path):
        j = self._j(tmp_path)
        j.reset()
        j.record("q1", 1.0, "Completed")
        path = tmp_path / "q.json"
        path.write_text(path.read_text()[:-10])  # torn write
        before = obs_metrics.snapshot()
        j2 = self._j(tmp_path)
        assert not j2.load()                     # fresh, not a crash
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["journal_resets_total"] == 1

    def test_config_drift_refuses(self, tmp_path):
        from nds_tpu.resilience.journal import JournalMismatch
        j = self._j(tmp_path, digest="aaaa")
        j.reset()
        j.record("q1", 1.0, "Completed")
        with pytest.raises(JournalMismatch):
            self._j(tmp_path, digest="bbbb").load()

    def test_mark_aborted_never_clobbers_a_completion(self, tmp_path):
        j = self._j(tmp_path)
        j.reset()
        j.start("q1")
        j.mark_aborted("q1")
        assert j.entry("q1")["aborted"] == "drain-deadline"
        # a completion wins over (and clears) the abort stamp
        j.record("q1", 5.0, "Completed")
        assert "aborted" not in j.entry("q1")
        j.mark_aborted("q1")
        assert "aborted" not in j.entry("q1")
        j.mark_aborted(None)  # no-op without a query


# ------------------------------------------------- preemption drain

class TestDrain:
    @pytest.fixture(autouse=True)
    def _clean(self):
        from nds_tpu.resilience import drain
        yield
        drain.uninstall()

    def _install(self, tmp_path, drain_s=30.0):
        from nds_tpu.resilience import drain
        exits = []
        dm = drain.install(drain_s, str(tmp_path),
                           _exit=lambda code: exits.append(code))
        return drain, dm, exits

    def test_boundary_exit_is_resumable(self, tmp_path):
        import signal as _sig
        drain, dm, exits = self._install(tmp_path)
        assert not drain.requested()
        drain.check_boundary()      # no-op before any signal
        os.kill(os.getpid(), _sig.SIGTERM)
        time.sleep(0.05)            # handler runs between bytecodes
        assert drain.requested()
        with pytest.raises(SystemExit) as ei:
            drain.check_boundary()
        assert ei.value.code == drain.EXIT_RESUMABLE == 75
        assert exits == []          # graceful path: no force exit

    def test_deadline_force_exits_after_flush_hooks(self, tmp_path):
        import signal as _sig
        drain, dm, exits = self._install(tmp_path, drain_s=0.15)
        flushed = []
        dm.add_flush_hook(lambda: flushed.append("journal"))
        os.kill(os.getpid(), _sig.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.02)        # in-flight "query" never finishes
        assert exits == [75]
        assert flushed == ["journal"]

    def test_repeat_signal_forces_immediately(self, tmp_path):
        import signal as _sig
        drain, dm, exits = self._install(tmp_path, drain_s=300.0)
        os.kill(os.getpid(), _sig.SIGTERM)
        time.sleep(0.05)
        assert exits == []          # still draining
        os.kill(os.getpid(), _sig.SIGTERM)
        time.sleep(0.05)
        assert exits == [75]        # operator said NOW

    def test_install_uninstall_restores_handlers(self, tmp_path):
        import signal as _sig
        from nds_tpu.resilience import drain
        prev_term = _sig.getsignal(_sig.SIGTERM)
        prev_int = _sig.getsignal(_sig.SIGINT)
        dm = drain.install(1.0, str(tmp_path), _exit=lambda c: None)
        assert _sig.getsignal(_sig.SIGTERM) == dm._on_signal
        drain.uninstall()
        assert _sig.getsignal(_sig.SIGTERM) == prev_term
        assert _sig.getsignal(_sig.SIGINT) == prev_int

    def test_finished_manager_chains_to_previous(self, tmp_path):
        """A signal landing after the drain stood down behaves like
        the handler we replaced (the chain contract NDS114 guards)."""
        drain, dm, exits = self._install(tmp_path)
        import signal as _sig
        seen = []
        dm._prev[_sig.SIGTERM] = lambda s, f: seen.append(s)
        dm._finished.set()
        dm._on_signal(_sig.SIGTERM, None)
        assert seen == [int(_sig.SIGTERM)] and exits == []

    def test_drain_seconds_resolution(self, monkeypatch):
        from nds_tpu.resilience import drain
        monkeypatch.delenv(drain.DRAIN_ENV, raising=False)
        assert drain.drain_seconds(None) == 30.0
        monkeypatch.setenv(drain.DRAIN_ENV, "7.5")
        assert drain.drain_seconds(None) == 7.5
        cfg = EngineConfig(overrides={"engine.drain_s": "12"})
        assert drain.drain_seconds(cfg) == 12.0
        monkeypatch.setenv(drain.DRAIN_ENV, "junk")
        assert drain.drain_seconds(None) == 30.0


# --------------------------------------- query-granular power resume

class TestPowerResume:
    def _journal(self, jsons):
        from nds_tpu.resilience.journal import QueryJournal
        return QueryJournal(os.path.join(jsons,
                                         "power-nds_queries.json"))

    def test_fresh_run_journals_every_statement(self, mini_wh,
                                                tmp_path):
        _failures, sums = _run_stream(mini_wh, tmp_path)
        j = self._journal(str(tmp_path / "json"))
        assert j.load()
        done = j.completed()
        assert sorted(done) == ["query7", "query93", "query96"]
        for q, e in done.items():
            assert e["incarnation"] == 0 and e["starts"] == [0]
            assert e["result_digest"]
            # the digest in the journal matches the summary's
            assert sums[q]["result_digest"] == e["result_digest"]
            assert sums[q]["incarnation"] == 0

    def test_resume_replays_done_and_runs_only_the_rest(self, mini_wh,
                                                        tmp_path):
        from nds_tpu.nds.power import SUITE
        from nds_tpu.utils.timelog import TimeLog
        _failures, sums0 = _run_stream(mini_wh, tmp_path)
        jsons = str(tmp_path / "json")
        j = self._journal(jsons)
        assert j.load()
        walls = {q: e["wall_ms"] for q, e in j.completed().items()}
        digests = {q: e["result_digest"]
                   for q, e in j.completed().items()}
        # simulate an interruption after query96: drop the later
        # completions (their starts stay — they DID start once)
        for q in ("query7", "query93"):
            j.state["queries"][q].pop("done")
        j.write()
        cfg = EngineConfig(overrides={
            "engine.backend": "cpu",
            "engine.retry.base_delay_s": "0.01"})
        failures = power_core.run_query_stream(
            SUITE, mini_wh["raw"], mini_wh["stream"],
            str(tmp_path / "time2.csv"), config=cfg,
            input_format="raw", json_summary_folder=jsons,
            resume=True)
        assert failures == 0
        j2 = self._journal(jsons)
        assert j2.load()
        done = j2.completed()
        assert sorted(done) == ["query7", "query93", "query96"]
        # query96 was REPLAYED: wall preserved, never re-executed
        assert done["query96"]["starts"] == [0]
        assert done["query96"]["wall_ms"] == walls["query96"]
        # the others re-ran in incarnation 1 with identical results
        for q in ("query7", "query93"):
            assert done[q]["incarnation"] == 1
            assert done[q]["starts"] == [0, 1]
            assert done[q]["result_digest"] == digests[q]
        # the resumed time log covers the WHOLE phase
        rows = {q: ms for _a, q, ms in TimeLog.read(
            str(tmp_path / "time2.csv"))}
        for q in ("query96", "query7", "query93"):
            assert q in rows
        assert rows["query96"] == int(walls["query96"])
        assert rows["Power Test Time"] > 0
        # one merged phase report, every statement billed once
        with open(os.path.join(jsons, "merged-power-nds.json")) as f:
            merged = json.load(f)
        assert merged["incarnations"] == 2
        assert sorted(merged["queries"]) == ["query7", "query93",
                                            "query96"]
        assert set(merged["queryStatus"]) == {"Completed"}

    def test_resume_refuses_config_drift(self, mini_wh, tmp_path):
        from nds_tpu.nds.power import SUITE
        from nds_tpu.resilience.journal import JournalMismatch
        _run_stream(mini_wh, tmp_path)
        cfg = EngineConfig(overrides={
            "engine.backend": "cpu",
            "engine.retry.base_delay_s": "0.5"})  # different config
        with pytest.raises(JournalMismatch):
            power_core.run_query_stream(
                SUITE, mini_wh["raw"], mini_wh["stream"],
                str(tmp_path / "t2.csv"), config=cfg,
                input_format="raw",
                json_summary_folder=str(tmp_path / "json"),
                resume=True)

    def test_fresh_run_resets_stale_query_journal(self, mini_wh,
                                                  tmp_path):
        _run_stream(mini_wh, tmp_path, subset=["query96"])
        j = self._journal(str(tmp_path / "json"))
        assert j.load()
        assert sorted(j.completed()) == ["query96"]
        # a later NON-resume run must not splice the stale journal
        _run_stream(mini_wh, tmp_path, subset=["query93"])
        j2 = self._journal(str(tmp_path / "json"))
        j2.load()
        assert sorted(j2.completed()) == ["query93"]

    def test_torn_query_journal_degrades_to_fresh(self, mini_wh,
                                                  tmp_path):
        from nds_tpu.nds.power import SUITE
        _run_stream(mini_wh, tmp_path, subset=["query96"])
        jpath = os.path.join(str(tmp_path / "json"),
                             "power-nds_queries.json")
        with open(jpath, "r+b") as f:
            f.seek(8)
            b = f.read(1)
            f.seek(8)
            f.write(bytes([b[0] ^ 0xFF]))
        before = obs_metrics.snapshot()
        cfg = EngineConfig(overrides={
            "engine.backend": "cpu",
            "engine.retry.base_delay_s": "0.01"})
        failures = power_core.run_query_stream(
            SUITE, mini_wh["raw"], mini_wh["stream"],
            str(tmp_path / "t3.csv"), config=cfg, input_format="raw",
            json_summary_folder=str(tmp_path / "json"),
            query_subset=["query96"], resume=True)
        assert failures == 0
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["journal_resets_total"] == 1
        # the degradation surfaces in the run's summaries
        _f, sums = 0, {}
        for f in os.listdir(str(tmp_path / "json")):
            with open(os.path.join(str(tmp_path / "json"), f)) as fh:
                s = json.load(fh)
            if isinstance(s, dict) and s.get("query") == "query96":
                sums[s["startTime"]] = s
        latest = sums[max(sums)]
        assert latest["degradations"]["journal_resets"] >= 1


# ----------------------------------- supervisor resumable exits

class TestSupervisorResume:
    def test_exit_75_resumes_without_charging_restarts(self, tmp_path):
        out = str(tmp_path)
        before = obs_metrics.snapshot()
        spec = _script_spec("s1", out, ["raise SystemExit(75)", "pass"])
        # ZERO restart budget: only the resumable contract relaunches
        sup = supervise.StreamSupervisor([spec], out, poll_s=0.05,
                                         max_restarts=0)
        _elapse, codes, summary = sup.run()
        s = summary["streams"]["s1"]
        assert codes == [0]
        assert s["exit_codes"] == [75, 0]
        assert s["restarts"] == 0 and s["resumes"] == 1
        assert s["degraded"]
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"].get("stream_resumes_total") == 1
        assert not d["counters"].get("stream_restarts_total")

    def test_resume_budget_is_bounded(self, tmp_path):
        out = str(tmp_path)
        spec = _script_spec("s1", out, ["raise SystemExit(75)"])
        sup = supervise.StreamSupervisor([spec], out, poll_s=0.05,
                                         max_restarts=0, max_resumes=2)
        _elapse, codes, summary = sup.run()
        s = summary["streams"]["s1"]
        assert s["exit_codes"] == [75, 75, 75]  # initial + 2 resumes
        assert s["resumes"] == 2 and codes == [75]

    def test_skipped_queries_enumerated(self, tmp_path):
        out = str(tmp_path)
        spec = _script_spec("s1", out, ["raise SystemExit(3)"],
                            queries=["q1", "q2", "q3"])
        sup = supervise.StreamSupervisor([spec], out, poll_s=0.05,
                                         max_restarts=1)
        _elapse, codes, summary = sup.run()
        s = summary["streams"]["s1"]
        assert codes == [3]
        # nothing ever completed: the whole stream is the gap, named
        assert s["skipped_queries"] == ["q1", "q2", "q3"]
        ondisk = json.load(open(os.path.join(out,
                                             supervise.SUMMARY_NAME)))
        assert ondisk["streams"]["s1"]["skipped_queries"] == \
            ["q1", "q2", "q3"]

    def test_successful_stream_lists_no_skips(self, tmp_path):
        out = str(tmp_path)
        spec = _script_spec("s1", out, ["pass"], queries=["q1"])
        sup = supervise.StreamSupervisor([spec], out, poll_s=0.05)
        _elapse, _codes, summary = sup.run()
        assert "skipped_queries" not in summary["streams"]["s1"]
        assert summary["streams"]["s1"]["resumes"] == 0


# ----------------------------------- transcode table-granular resume

class TestTranscodeResume:
    TABLES = ["warehouse", "income_band"]

    def _transcode(self, mini_wh, out, resume=False):
        from nds_tpu.nds.transcode import transcode
        return transcode(mini_wh["raw"], out,
                         os.path.join(out, "report.txt"),
                         tables=self.TABLES, resume=resume)

    def test_resume_skips_verified_tables(self, mini_wh, tmp_path):
        out = str(tmp_path / "wh")
        first = self._transcode(mini_wh, out)
        assert all(first[t] > 0 for t in self.TABLES)
        mtimes = {}
        for t in self.TABLES:
            tdir = os.path.join(out, t)
            mtimes[t] = {f: os.stat(os.path.join(tdir, f)).st_mtime_ns
                         for f in os.listdir(tdir)}
        # resume: every manifest verifies -> nothing re-transcodes
        second = self._transcode(mini_wh, out, resume=True)
        assert all(second[t] == 0.0 for t in self.TABLES)
        for t in self.TABLES:
            tdir = os.path.join(out, t)
            now = {f: os.stat(os.path.join(tdir, f)).st_mtime_ns
                   for f in os.listdir(tdir)}
            assert now == mtimes[t]  # bytes untouched

    def test_resume_rebuilds_missing_and_corrupt_tables(self, mini_wh,
                                                        tmp_path):
        import shutil
        out = str(tmp_path / "wh")
        self._transcode(mini_wh, out)
        # SIGTERM-mid-load analog: one table's output never finished
        shutil.rmtree(os.path.join(out, "income_band"))
        # ...and another's bytes were corrupted on disk
        wdir = os.path.join(out, "warehouse")
        data = [f for f in os.listdir(wdir)
                if not f.startswith("_")][0]
        p = os.path.join(wdir, data)
        with open(p, "r+b") as f:
            f.seek(20)
            b = f.read(1)
            f.seek(20)
            f.write(bytes([b[0] ^ 0xFF]))
        integrity.clear_cache()
        redo = self._transcode(mini_wh, out, resume=True)
        assert redo["income_band"] > 0   # missing: rebuilt
        assert redo["warehouse"] > 0     # corrupt: rebuilt
        # and now everything verifies again
        assert integrity.verify_manifest(wdir)

    def test_non_resume_always_retranscodes(self, mini_wh, tmp_path):
        out = str(tmp_path / "wh")
        self._transcode(mini_wh, out)
        again = self._transcode(mini_wh, out)   # no resume flag
        assert all(again[t] > 0 for t in self.TABLES)

    def test_verify_manifest_contract(self, tmp_path):
        d = str(tmp_path / "t")
        os.makedirs(d)
        assert not integrity.verify_manifest(d)  # no manifest
        with open(os.path.join(d, "part-0.bin"), "wb") as f:
            f.write(b"payload")
        integrity.write_manifest(d)
        assert integrity.verify_manifest(d)
        with open(os.path.join(d, "part-0.bin"), "wb") as f:
            f.write(b"tampered")
        assert not integrity.verify_manifest(d)
        os.unlink(os.path.join(d, "part-0.bin"))
        assert not integrity.verify_manifest(d)  # missing file


class TestBenchResumableExit:
    def test_power_exit_75_retries_with_resume(self, tmp_path,
                                               monkeypatch):
        """A power subprocess that drains (exit 75) is re-run with
        --resume instead of failing the bench, and never counts as a
        failed phase."""
        from nds_tpu.nds import bench as bench_mod
        from nds_tpu.utils.timelog import TimeLog
        calls = []
        maint_calls = []
        rcs = [75, 75, 0]

        def fake_run(cmd, backend=None, extra_env=None):
            if cmd[2] == "nds_tpu.nds.transcode":
                with open(cmd[5], "w") as f:
                    f.write("Total conversion time for 24 tables was "
                            "5.0s\nRNGSEED used: 123\n")

        def fake_run_rc(cmd, backend=None, extra_env=None):
            # maintenance rides _run_rc too (its commit journal makes
            # exit 75 resumable); here it just succeeds
            if cmd[2] == "nds_tpu.nds.maintenance":
                maint_calls.append(list(cmd))
                t = TimeLog("fake")
                t.add("Data Maintenance Time", 1500)
                t.write(cmd[5])
                return 0
            calls.append(list(cmd))
            rc = rcs.pop(0)
            if rc == 0:
                t = TimeLog("fake")
                t.add("Power Test Time", 2000)
                t.write(cmd[5])
            return rc

        monkeypatch.setattr(bench_mod, "_run", fake_run)
        monkeypatch.setattr(bench_mod, "_run_rc", fake_run_rc)
        import nds_tpu.nds.streams as streams_mod
        import nds_tpu.nds.throughput as tp_mod
        monkeypatch.setattr(streams_mod, "generate_query_streams",
                            lambda *a, **kw: None)
        monkeypatch.setattr(tp_mod, "run_streams",
                            lambda *a, **kw: (3.0, [0]))
        monkeypatch.setattr(tp_mod, "run_streams_inprocess",
                            lambda *a, **kw: (3.0, [0]))
        work = tmp_path / "w"
        cfg = {"scale_factor": 0.01, "parallel": 2, "num_streams": 1,
               "backend": "cpu",
               "paths": {"raw_data": str(work / "raw"),
                         "warehouse": str(work / "wh"),
                         "streams": str(work / "streams"),
                         "reports": str(work / "reports")},
               "skip": {"data_gen": True}}
        metrics = bench_mod.run_full_bench(cfg)
        assert metrics["metric"] is not None
        assert len(calls) == 3
        assert "--resume" not in calls[0]       # fresh first launch
        assert "--resume" in calls[1]           # both retries resume
        assert "--resume" in calls[2]
        assert len(maint_calls) == 2            # one per round
        assert all("--resume" not in c for c in maint_calls)

    def test_power_non_resumable_failure_still_raises(self, tmp_path,
                                                      monkeypatch):
        import subprocess as sp

        from nds_tpu.nds import bench as bench_mod
        monkeypatch.setattr(bench_mod, "_run",
                            lambda *a, **kw: None)
        monkeypatch.setattr(bench_mod, "_run_rc",
                            lambda *a, **kw: 1)
        monkeypatch.setattr(bench_mod, "get_load_time",
                            lambda p: 5.0)
        monkeypatch.setattr(bench_mod, "get_rngseed", lambda p: 123)
        import nds_tpu.nds.streams as streams_mod
        monkeypatch.setattr(streams_mod, "generate_query_streams",
                            lambda *a, **kw: None)
        work = tmp_path / "w"
        cfg = {"scale_factor": 0.01, "parallel": 2, "num_streams": 1,
               "backend": "cpu",
               "paths": {"raw_data": str(work / "raw"),
                         "warehouse": str(work / "wh"),
                         "streams": str(work / "streams"),
                         "reports": str(work / "reports")},
               "skip": {"data_gen": True}}
        with pytest.raises(sp.CalledProcessError):
            bench_mod.run_full_bench(cfg)


class TestReviewFixes:
    def test_transcode_resume_refuses_option_drift(self, mini_wh,
                                                   tmp_path):
        from nds_tpu.nds.transcode import transcode
        out = str(tmp_path / "wh")
        transcode(mini_wh["raw"], out,
                  os.path.join(out, "r.txt"), tables=["warehouse"])
        # same options resume: fine
        transcode(mini_wh["raw"], out, os.path.join(out, "r2.txt"),
                  tables=["warehouse"], resume=True)
        # different schema mode: the finished tables' manifests still
        # verify, so a silent skip would yield a mixed warehouse —
        # refuse loudly instead
        with pytest.raises(ValueError, match="different transcode"):
            transcode(mini_wh["raw"], out, os.path.join(out, "r3.txt"),
                      tables=["warehouse"], resume=True,
                      use_decimal=False)

    def test_restarted_incarnation_keeps_journal(self, mini_wh,
                                                 tmp_path,
                                                 monkeypatch):
        """A supervisor-relaunched incarnation (unit '<name>#rN') must
        LOAD the shared journal, not reset it: the first incarnation's
        completion records are the no-double-execution evidence."""
        from nds_tpu.nds.power import SUITE
        jsons = str(tmp_path / "json")
        cfg = {"engine.backend": "cpu",
               "engine.retry.base_delay_s": "0.01"}
        monkeypatch.setenv(watchdog.STREAM_ENV, "s9")
        power_core.run_query_stream(
            SUITE, mini_wh["raw"], mini_wh["stream"],
            str(tmp_path / "t1.csv"),
            config=EngineConfig(overrides=cfg), input_format="raw",
            json_summary_folder=jsons, query_subset=["query96"])
        # the relaunched incarnation runs the REMAINING subset
        monkeypatch.setenv(watchdog.STREAM_ENV, "s9#r1")
        power_core.run_query_stream(
            SUITE, mini_wh["raw"], mini_wh["stream"],
            str(tmp_path / "t2.csv"),
            config=EngineConfig(overrides=cfg), input_format="raw",
            json_summary_folder=jsons,
            query_subset=["query7", "query93"])
        from nds_tpu.resilience.journal import QueryJournal
        j = QueryJournal(os.path.join(jsons, "s9_queries.json"))
        assert j.load()
        done = j.completed()
        # incarnation 0's record SURVIVED the relaunch
        assert done["query96"]["incarnation"] == 0
        assert done["query7"]["incarnation"] == 1
        assert done["query93"]["incarnation"] == 1
