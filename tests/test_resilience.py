"""Resilience layer tests: seeded fault injection, failure
classification, RetryPolicy backoff/deadline semantics, the power-loop
retry + fallback integration, thread-safe failure collection, the
NDS108 naked-retry lint rule, the resumable bench journal, chunked-
executor OOM degradation, and throughput stream failure reports."""

import json
import os
import threading

import pytest

from nds_tpu.analysis import lint_rules
from nds_tpu.nds import gen_data, streams
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.resilience import faults
from nds_tpu.resilience.journal import (
    JournalMismatch, PhaseJournal, config_digest,
)
from nds_tpu.resilience.retry import (
    DETERMINISTIC, TRANSIENT, RetryPolicy, RetryStats, classify, is_oom,
)
from nds_tpu.utils import power_core
from nds_tpu.utils.config import EngineConfig


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def mini_wh(tmp_path_factory):
    """Tiny raw NDS warehouse + a 3-query stream (raw format: the
    power loop reads .dat directly, no transcode needed)."""
    root = tmp_path_factory.mktemp("resilience")
    raw = str(root / "raw")
    gen_data.generate_data_local(0.01, 2, raw, workers=2)
    sdir = str(root / "streams")
    streams.generate_query_streams(sdir, 1, templates=[96, 7, 93])
    return {"raw": raw, "stream": os.path.join(sdir, "query_0.sql"),
            "root": str(root)}


# ------------------------------------------------------- fault harness

class TestFaultSchedule:
    def test_parse_full_syntax(self):
        specs = faults.parse_schedule(
            "device.execute:oom@q5,io.read:delay=0.2@*,"
            "exchange:fault*3~0.5@query1*")
        assert [s.site for s in specs] == ["device.execute", "io.read",
                                          "exchange"]
        assert specs[0].times == 1          # raising kinds default once
        assert specs[1].times is None       # delay defaults unlimited
        assert specs[1].param == 0.2
        assert specs[2].times == 3 and specs[2].prob == 0.5

    @pytest.mark.parametrize("bad", [
        "nonsense", "plan:oom",             # missing scope
        "bogus.site:oom@*",                 # unknown site
        "plan:explode@*",                   # unknown kind
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            faults.parse_schedule(bad)

    def test_scope_q_alias_and_fnmatch(self):
        assert faults._scope_matches("q5", {"query": "query5"})
        assert not faults._scope_matches("q5", {"query": "query55"})
        assert faults._scope_matches("query5*", {"query": "query55"})
        assert faults._scope_matches("*", {})
        assert faults._scope_matches("store_*", {"table": "store_sales"})

    def test_times_budget_lets_retry_succeed(self):
        faults.install("plan:oom@*")
        with pytest.raises(faults.InjectedOOM):
            faults.fault_point("plan")
        faults.fault_point("plan")  # budget spent: the retry passes

    def test_context_and_suppress(self):
        faults.install("device.execute:fault@q7")
        faults.fault_point("device.execute")  # no context: no match
        with faults.context(query="query7"):
            with faults.suppress():
                faults.fault_point("device.execute")  # warmup analog
            with pytest.raises(faults.InjectedTransientFault):
                faults.fault_point("device.execute")

    def test_probability_replays_from_seed(self):
        def firing_pattern(seed):
            plan = faults.install("plan:fault*999~0.4@*", seed=seed)
            fired = []
            for _ in range(40):
                try:
                    faults.fault_point("plan")
                    fired.append(0)
                except faults.InjectedTransientFault:
                    fired.append(1)
            faults.clear()
            return fired, plan.specs[0].fired

        a, na = firing_pattern(3)
        b, nb = firing_pattern(3)
        c, _ = firing_pattern(4)
        assert a == b and na == nb      # exact replay from the seed
        assert 0 < na < 40              # probabilistic, not all-or-none
        assert a != c                   # the seed actually matters

    def test_env_schedule_and_zero_cost_unset(self, monkeypatch):
        monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
        faults.clear()
        faults.fault_point("plan")      # unset: pure no-op
        monkeypatch.setenv(faults.FAULTS_ENV, "plan:deterministic@*")
        with pytest.raises(faults.InjectedDeterministicFault):
            faults.fault_point("plan")

    def test_env_seed_change_rebuilds_plan(self, monkeypatch):
        """The env cache keys on (schedule, seed): changing only the
        seed must rebuild the plan (fresh fired-counts, new RNG)."""
        monkeypatch.setenv(faults.FAULTS_ENV, "plan:fault*999~0.5@*")
        monkeypatch.setenv(faults.SEED_ENV, "1")
        faults.clear()

        def pattern():
            fired = []
            for _ in range(30):
                try:
                    faults.fault_point("plan")
                    fired.append(0)
                except faults.InjectedTransientFault:
                    fired.append(1)
            return fired

        a = pattern()
        monkeypatch.setenv(faults.SEED_ENV, "2")
        b = pattern()
        assert a != b                   # new seed actually took effect
        monkeypatch.setenv(faults.SEED_ENV, "1")
        assert pattern() == a           # and replays exactly again


# ------------------------------------------------------ classification

class TestClassify:
    def test_vocabulary(self):
        assert classify(faults.InjectedOOM("x", "boom")) == TRANSIENT
        assert classify(
            faults.InjectedTransientFault("x", "boom")) == TRANSIENT
        assert classify(
            faults.InjectedDeterministicFault("x", "boom")) \
            == DETERMINISTIC
        # jaxlib's device-OOM message shape
        assert classify(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 1 bytes")) \
            == TRANSIENT
        from nds_tpu.engine.device_exec import DeviceExecError
        assert classify(DeviceExecError(
            "exchange overflow persisted after retries")) == TRANSIENT
        # parse/plan/verify-style errors never retry
        assert classify(ValueError("no such column")) == DETERMINISTIC
        assert classify(KeyError("tbl")) == DETERMINISTIC

    def test_is_oom(self):
        assert is_oom(faults.InjectedOOM("x", "injected"))
        assert is_oom(RuntimeError("RESOURCE_EXHAUSTED: ..."))
        assert is_oom(RuntimeError("Out of memory allocating"))
        assert not is_oom(faults.InjectedTransientFault("x", "generic"))


# -------------------------------------------------------- retry policy

class TestRetryPolicy:
    def _policy(self, **kw):
        kw.setdefault("sleep", lambda d: None)
        return RetryPolicy(**kw)

    def test_transient_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise faults.InjectedOOM("s", "injected oom")
            return "ok"

        st = RetryStats()
        before = obs_metrics.snapshot()
        assert self._policy(max_attempts=3).call(flaky, stats=st) == "ok"
        assert st.attempts == 3 and st.retries == 2
        assert st.gave_up_reason is None
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["query_retries_total"] == 2

    def test_deterministic_never_retries(self):
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("planner bug")

        st = RetryStats()
        with pytest.raises(ValueError):
            self._policy(max_attempts=5).call(broken, stats=st)
        assert len(calls) == 1 and st.retries == 0
        assert st.gave_up_reason == "deterministic"

    def test_attempt_cap_exhausts(self):
        def always():
            raise faults.InjectedOOM("s", "injected oom")

        st = RetryStats()
        with pytest.raises(faults.InjectedOOM):
            self._policy(max_attempts=3).call(always, stats=st)
        assert st.attempts == 3
        assert st.gave_up_reason == "attempts_exhausted(3)"

    def test_backoff_exponential_jittered_seeded(self):
        p1 = self._policy(base_delay_s=0.1, max_delay_s=10.0,
                          jitter=0.25, seed=11)
        p2 = self._policy(base_delay_s=0.1, max_delay_s=10.0,
                          jitter=0.25, seed=11)
        p3 = self._policy(base_delay_s=0.1, max_delay_s=10.0,
                          jitter=0.25, seed=12)
        d1 = [p1.delay_for(i) for i in range(5)]
        assert d1 == [p2.delay_for(i) for i in range(5)]  # seeded
        assert d1 != [p3.delay_for(i) for i in range(5)]
        for i, d in enumerate(d1):
            base = 0.1 * 2 ** i
            assert base <= d <= base * 1.25     # exp + bounded jitter
        # the cap clamps the base term
        assert self._policy(base_delay_s=1.0, max_delay_s=2.0,
                            jitter=0.0).delay_for(6) == 2.0

    def test_deadline_stops_retrying(self):
        t = {"now": 0.0}

        def clock():
            return t["now"]

        def sleep(d):
            t["now"] += d

        def always():
            t["now"] += 1.0
            raise faults.InjectedOOM("s", "injected oom")

        st = RetryStats()
        p = RetryPolicy(max_attempts=100, base_delay_s=0.5,
                        jitter=0.0, deadline_s=2.0, sleep=sleep,
                        clock=clock)
        before = obs_metrics.snapshot()
        with pytest.raises(faults.InjectedOOM):
            p.call(always, stats=st)
        assert st.gave_up_reason == "deadline"
        assert st.deadline_exceeded
        assert st.attempts < 100
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["query_deadline_exceeded_total"] == 1

    def test_success_past_deadline_is_flagged(self):
        t = {"now": 0.0}

        def slow():
            t["now"] += 5.0
            return 42

        st = RetryStats()
        p = RetryPolicy(deadline_s=1.0, clock=lambda: t["now"],
                        sleep=lambda d: None)
        assert p.call(slow, stats=st) == 42
        assert st.deadline_exceeded and st.gave_up_reason is None

    def test_from_config(self):
        cfg = EngineConfig(overrides={
            "engine.retry.max_attempts": "5",
            "engine.retry.base_delay_s": "0.5",
            "engine.retry.max_delay_s": "9",
            "engine.retry.jitter": "0",
            "engine.query_deadline_s": "30",
        })
        p = RetryPolicy.from_config(cfg)
        assert p.max_attempts == 5 and p.base_delay_s == 0.5
        assert p.max_delay_s == 9 and p.deadline_s == 30.0
        # absent/zero deadline means none
        assert RetryPolicy.from_config(EngineConfig()).deadline_s is None

    def test_attempts_iterator_sleeps_between(self):
        slept = []
        p = RetryPolicy(max_attempts=4, base_delay_s=0.1, jitter=0.0,
                        sleep=slept.append)
        assert list(p.attempts()) == [0, 1, 2, 3]
        assert slept == [0.1, 0.2, 0.4]

    def test_with_attempts_preserves_everything_else(self):
        slept = []
        p = RetryPolicy(max_attempts=5, base_delay_s=0.2,
                        max_delay_s=7.0, jitter=0.5, deadline_s=30.0,
                        seed=3, sleep=slept.append)
        q = p.with_attempts(2)
        assert q.max_attempts == 2
        assert (q.base_delay_s, q.max_delay_s, q.jitter, q.deadline_s,
                q.seed) == (0.2, 7.0, 0.5, 30.0, 3)
        assert q._sleep is p._sleep and q._clock is p._clock


# ----------------------------------------------- failure collector

class TestTaskFailureCollector:
    def test_concurrent_notify_and_dedup(self):
        from nds_tpu.utils.report import TaskFailureCollector
        col = TaskFailureCollector()
        col.register()
        try:
            def hammer(i):
                for _ in range(50):
                    TaskFailureCollector.notify("overflow retry")
                TaskFailureCollector.notify(f"unique-{i}")

            threads = [threading.Thread(target=hammer, args=(i,))
                       for i in range(8)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        finally:
            col.unregister()
        # deduplicated: one entry for the repeated reason + 8 uniques
        assert col.failures.count("overflow retry") == 1
        assert len(col.failures) == 9
        fmt = col.formatted()
        assert "overflow retry (x400)" in fmt
        assert "unique-3" in fmt

    def test_report_carries_dedup_counts(self):
        from nds_tpu.utils.report import BenchReport, TaskFailureCollector

        def body():
            for _ in range(3):
                TaskFailureCollector.notify("slack retry")

        rep = BenchReport("q")
        s = rep.report_on(body)
        assert s["queryStatus"] == ["CompletedWithTaskFailures"]
        assert s["exceptions"] == ["slack retry (x3)"]


# ------------------------------------------------------ NDS108 lint

def _lint(src: str, enabled=None):
    return lint_rules.lint_sources({"nds_tpu/x.py": src},
                                   enabled=enabled)


def _rules(violations):
    return {v.rule for v in violations}


class TestNakedRetryRule:
    def test_uncapped_while_true_flags(self):
        src = ("import time\n"
               "def f(op):\n"
               "    while True:\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            time.sleep(1)\n")
        assert _rules(_lint(src, enabled={"NDS108"}).violations) \
            == {"NDS108"}

    def test_constant_sleep_in_capped_loop_flags(self):
        src = ("import time\n"
               "def f(op):\n"
               "    for i in range(5):\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            time.sleep(0.5)\n")
        assert _rules(_lint(src, enabled={"NDS108"}).violations) \
            == {"NDS108"}

    def test_backoff_and_cap_is_clean(self):
        src = ("import time\n"
               "def f(op):\n"
               "    delay = 0.1\n"
               "    for i in range(5):\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            time.sleep(delay)\n"
               "            delay *= 2\n")
        assert _lint(src, enabled={"NDS108"}).violations == []

    def test_loop_without_sleep_is_clean(self):
        src = ("def f(op):\n"
               "    for i in range(3):\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            pass\n")
        assert _lint(src, enabled={"NDS108"}).violations == []

    def test_waiver_applies(self):
        # the standalone waiver covers the next line (the flagged
        # `while True`)
        src = ("import time\n"
               "def f(op):\n"
               "    # ndslint: waive[NDS108] -- external rate limit "
               "mandates a fixed poll interval\n"
               "    while True:\n"
               "        try:\n"
               "            return op()\n"
               "        except Exception:\n"
               "            time.sleep(1)\n")
        res = _lint(src, enabled={"NDS108"})
        assert res.violations == [] and len(res.waived) == 1

    def test_in_default_rules(self):
        assert any(r.id == "NDS108"
                   for r in lint_rules.default_rules())


# ------------------------------------------------------ phase journal

class TestPhaseJournal:
    def test_round_trip_and_digest_guard(self, tmp_path):
        path = str(tmp_path / "bench_state.json")
        dg = config_digest({"scale": 1})
        j = PhaseJournal(path, dg)
        j.reset()
        j.complete("load_test", load_time_s=5.5, rngseed=99)
        j2 = PhaseJournal(path, dg)
        assert j2.load()
        assert j2.done("load_test") and not j2.done("power_test")
        assert j2.timings("load_test") == {"load_time_s": 5.5,
                                           "rngseed": 99}
        with pytest.raises(JournalMismatch):
            PhaseJournal(path, config_digest({"scale": 2})).load()

    def test_reset_drops_prior_state(self, tmp_path):
        path = str(tmp_path / "bench_state.json")
        j = PhaseJournal(path, "d")
        j.complete("power_test", power_time_s=1.0)
        j.reset()
        j2 = PhaseJournal(path, "d")
        assert not j2.load()

    def test_write_is_atomic(self, tmp_path):
        path = str(tmp_path / "bench_state.json")
        j = PhaseJournal(path, "d")
        j.complete("a", x=1)
        assert not os.path.exists(path + ".tmp")
        assert json.load(open(path))["phases"]["a"]["timings"] == {"x": 1}

    def test_missing_file_loads_empty(self, tmp_path):
        assert not PhaseJournal(str(tmp_path / "nope.json"), "d").load()


# --------------------------------------- power loop integration (cpu)

def _run_stream(mini_wh, tmp_path, overrides=None, subset=None,
                warmup=0):
    from nds_tpu.nds.power import SUITE
    cfg = EngineConfig(overrides={"engine.backend": "cpu",
                                  "engine.retry.base_delay_s": "0.01",
                                  **(overrides or {})})
    jsons = str(tmp_path / "json")
    failures = power_core.run_query_stream(
        SUITE, mini_wh["raw"], mini_wh["stream"],
        str(tmp_path / "time.csv"), config=cfg, input_format="raw",
        json_summary_folder=jsons, query_subset=subset, warmup=warmup)
    summaries = {}
    for f in os.listdir(jsons):
        with open(os.path.join(jsons, f)) as fh:
            s = json.load(fh)
        summaries[s["query"]] = s
    return failures, summaries


class TestPowerLoopResilience:
    def test_transient_oom_retried_to_completion(self, mini_wh,
                                                 tmp_path):
        faults.install("device.execute:oom@query7")
        failures, sums = _run_stream(mini_wh, tmp_path)
        assert failures == 0
        assert sums["query7"]["queryStatus"] == ["Completed"]
        assert sums["query7"]["retries"] == 1
        assert sums["query7"]["retry_backoff_s"] > 0
        assert sums["query96"]["retries"] == 0

    def test_plan_fault_fails_fast(self, mini_wh, tmp_path):
        faults.install("plan:deterministic@query96")
        failures, sums = _run_stream(mini_wh, tmp_path)
        assert failures == 1
        s = sums["query96"]
        assert s["queryStatus"] == ["Failed"]
        assert s["retries"] == 0
        assert s["gave_up_reason"] == "deterministic"
        assert any("injected deterministic" in e
                   for e in s["exceptions"])
        # the stream kept going past the failure
        assert sums["query7"]["queryStatus"] == ["Completed"]

    def test_plan_fault_fires_despite_warmup_plan_cache(self, mini_wh,
                                                        tmp_path):
        """The suppressed warmup pass plans and CACHES the query; the
        timed pass takes the plan-cache hit — the plan chaos site must
        still fire there (Session fires it on cache hits too)."""
        faults.install("plan:deterministic@query96")
        failures, sums = _run_stream(mini_wh, tmp_path,
                                     subset=["query96"], warmup=1)
        assert failures == 1
        assert sums["query96"]["queryStatus"] == ["Failed"]
        assert sums["query96"]["gave_up_reason"] == "deterministic"

    def test_query_deadline_flagged(self, mini_wh, tmp_path):
        before = obs_metrics.snapshot()
        failures, sums = _run_stream(
            mini_wh, tmp_path,
            overrides={"engine.query_deadline_s": "0.000001"},
            subset=["query96"])
        assert failures == 0
        assert sums["query96"]["deadline_exceeded"] is True
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["query_deadline_exceeded_total"] >= 1

    def test_fallback_to_cpu_after_repeated_device_failure(
            self, mini_wh, tmp_path):
        # tpu backend on the virtual-CPU mesh: both early queries
        # exhaust their attempts on injected OOM, the streak trips
        # engine.fallback=cpu, and the LAST query completes on the
        # CPU oracle
        faults.install("device.execute:oom*99@query96,"
                       "device.execute:oom*99@query7")
        before = obs_metrics.snapshot()
        failures, sums = _run_stream(
            mini_wh, tmp_path,
            overrides={"engine.backend": "tpu",
                       "engine.fallback": "cpu"})
        assert failures == 2
        assert sums["query96"]["gave_up_reason"] == \
            "attempts_exhausted(3)"
        assert sums["query7"]["gave_up_reason"] == \
            "attempts_exhausted(3)"
        assert sums["query93"]["queryStatus"] == ["Completed"]
        d = obs_metrics.delta(before, obs_metrics.snapshot())
        assert d["counters"]["engine_fallbacks_total"] == 1

    def test_allow_failure_exit_code_contract(self, mini_wh, tmp_path,
                                              monkeypatch):
        """--allow_failure end-to-end through the driver main: one
        injected deterministic failure exits 1 without the flag, 0
        with it, and the TimeLog CSV carries every query either way."""
        from nds_tpu.nds.power import main
        from nds_tpu.utils.timelog import TimeLog
        monkeypatch.setenv(faults.FAULTS_ENV,
                           "plan:deterministic@query96")
        faults.clear()  # drop any cached env plan

        def drive(tag, *extra):
            tlog = str(tmp_path / f"{tag}.csv")
            jsons = str(tmp_path / f"json_{tag}")
            with pytest.raises(SystemExit) as ei:
                main([mini_wh["raw"], mini_wh["stream"], tlog,
                      "--backend", "cpu", "--input_format", "raw",
                      "--json_summary_folder", jsons, *extra])
            names = [q for _a, q, _ms in TimeLog.read(tlog)]
            failed = 0
            for f in os.listdir(jsons):
                with open(os.path.join(jsons, f)) as fh:
                    if json.load(fh)["queryStatus"] == ["Failed"]:
                        failed += 1
            return ei.value.code, names, failed

        faults.clear()
        code, names, failed = drive("strict")
        assert code == 1 and failed == 1
        assert {"query96", "query7", "query93"} <= set(names)
        faults.clear()  # fresh budget for the second run
        code, names, failed = drive("lenient", "--allow_failure")
        assert code == 0 and failed == 1
        assert {"query96", "query7", "query93"} <= set(names)


# ------------------------------------------- chunked OOM degradation

def _chunked_session(mini_wh, chunk_rows):
    from nds_tpu.engine.chunked_exec import make_chunked_factory
    from nds_tpu.engine.session import Session
    from nds_tpu.io import csv_io
    from nds_tpu.nds.schema import get_schemas

    schema = get_schemas()["store_sales"]
    paths = [os.path.join(mini_wh["raw"], "store_sales", f)
             for f in sorted(os.listdir(
                 os.path.join(mini_wh["raw"], "store_sales")))]
    table = csv_io.read_tbl(paths, "store_sales", schema)
    sess = Session.for_nds(
        make_chunked_factory(stream_bytes=1, chunk_rows=chunk_rows))
    sess.register_table(table)
    return sess, table


def test_chunked_executor_halves_chunks_on_oom(mini_wh):
    sess, table = _chunked_session(mini_wh, chunk_rows=1 << 14)
    before = obs_metrics.snapshot()
    faults.install("device.execute:oom*2@*")
    res = sess.sql("select count(*) c from store_sales").to_pandas()
    assert int(res["c"][0]) == table.nrows
    ex = sess._executor_factory(sess.tables)
    # two OOMs -> two halvings before the third attempt succeeded
    assert ex.chunk_rows == 1 << 12
    d = obs_metrics.delta(before, obs_metrics.snapshot())
    assert d["counters"]["chunk_shrink_total"] == 2


def test_chunked_oom_at_floor_falls_back_to_full_upload(mini_wh):
    """With chunk_rows already at the halving floor, a partial-agg OOM
    must fall back to the full-upload phase B (the pre-resilience
    behavior), not fail the query."""
    sess, table = _chunked_session(mini_wh, chunk_rows=1 << 12)
    before = obs_metrics.snapshot()
    faults.install("device.execute:oom@*")
    res = sess.sql("select count(*) c from store_sales").to_pandas()
    assert int(res["c"][0]) == table.nrows
    ex = sess._executor_factory(sess.tables)
    assert ex.chunk_rows == 1 << 12     # no halving happened
    d = obs_metrics.delta(before, obs_metrics.snapshot())
    assert "chunk_shrink_total" not in d.get("counters", {})


# --------------------------------------- throughput stream reports

class TestThroughputResilience:
    @pytest.fixture(scope="class")
    def tstreams(self, mini_wh, tmp_path_factory):
        sdir = str(tmp_path_factory.mktemp("tstreams"))
        return streams.generate_query_streams(
            sdir, 2, rng_seed=7, templates=[96, 7],
            qualification=False)

    def _reports(self, out):
        reps = {}
        for f in os.listdir(out):
            if f.endswith(".json"):
                with open(os.path.join(out, f)) as fh:
                    s = json.load(fh)
                reps[s["query"]] = s
        return reps

    def test_clean_run_writes_stream_reports(self, mini_wh, tstreams,
                                             tmp_path):
        from nds_tpu.nds.throughput import run_streams_inprocess
        out = str(tmp_path / "tp")
        elapse, fails = run_streams_inprocess(
            mini_wh["raw"], tstreams, out, backend="cpu",
            input_format="raw")
        assert fails == [0, 0]
        reps = self._reports(out)
        assert set(reps) == {"query_0", "query_1"}
        for r in reps.values():
            assert r["queryStatus"] == ["Completed"] * 2
            assert r["exceptions"] == [] and r["retries"] == 0

    def test_transient_fault_retried_in_stream(self, mini_wh,
                                               tstreams, tmp_path):
        from nds_tpu.nds.throughput import run_streams_inprocess
        faults.install("device.execute:oom@query7")
        out = str(tmp_path / "tp")
        _elapse, fails = run_streams_inprocess(
            mini_wh["raw"], tstreams, out, backend="cpu",
            input_format="raw")
        assert fails == [0, 0]
        reps = self._reports(out)
        assert sum(r["retries"] for r in reps.values()) == 1
        for r in reps.values():
            assert r["queryStatus"] == ["Completed"] * 2

    def test_failure_text_lands_in_stream_report(self, mini_wh,
                                                 tstreams, tmp_path):
        from nds_tpu.nds.throughput import run_streams_inprocess
        faults.install("plan:deterministic@query96")
        out = str(tmp_path / "tp")
        _elapse, fails = run_streams_inprocess(
            mini_wh["raw"], tstreams, out, backend="cpu",
            input_format="raw")
        assert sum(fails) == 1
        reps = self._reports(out)
        failed = [r for r in reps.values() if "Failed" in
                  r["queryStatus"]]
        assert len(failed) == 1
        assert any("injected deterministic" in e
                   for e in failed[0]["exceptions"])


# --------------------------------------------------- resumable bench

class TestBenchResume:
    @staticmethod
    def _fake_phases(monkeypatch, calls):
        """Replace every subprocess phase with a recorder that writes
        the artifact the orchestrator reads back."""
        from nds_tpu.nds import bench as bench_mod
        from nds_tpu.utils.timelog import TimeLog

        def fake_run(cmd, backend=None, extra_env=None):
            calls.append(cmd[2])
            mod = cmd[2]
            if mod == "nds_tpu.nds.transcode":
                with open(cmd[5], "w") as f:
                    f.write("Total conversion time for 24 tables was "
                            "5.0s\nRNGSEED used: 123\n")
            elif mod == "nds_tpu.nds.power":
                t = TimeLog("fake")
                t.add("Power Test Time", 2000)
                t.write(cmd[5])
            elif mod == "nds_tpu.nds.maintenance":
                t = TimeLog("fake")
                t.add("Data Maintenance Time", 1500)
                t.write(cmd[5])

        def fake_streams(*a, **kw):
            calls.append("stream_gen")

        def fake_tp(*a, **kw):
            calls.append("throughput")
            return 3.0, [0]

        monkeypatch.setattr(bench_mod, "_run", fake_run)
        import nds_tpu.nds.streams as streams_mod
        import nds_tpu.nds.throughput as tp_mod
        monkeypatch.setattr(streams_mod, "generate_query_streams",
                            fake_streams)
        monkeypatch.setattr(tp_mod, "run_streams", fake_tp)
        monkeypatch.setattr(tp_mod, "run_streams_inprocess", fake_tp)

    def _cfg(self, tmp_path):
        work = tmp_path / "w"
        return {
            "scale_factor": 0.01, "parallel": 2, "num_streams": 1,
            "backend": "cpu",
            "paths": {
                "raw_data": str(work / "raw"),
                "warehouse": str(work / "wh"),
                "streams": str(work / "streams"),
                "reports": str(work / "reports"),
            },
            "skip": {},
        }

    def test_resume_skips_completed_phases(self, tmp_path,
                                           monkeypatch):
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        m1 = run_full_bench(cfg)
        assert m1["metric"] is not None and m1["metric"] > 0
        assert calls  # everything ran
        state = json.load(open(os.path.join(cfg["paths"]["reports"],
                                            "bench_state.json")))
        assert set(state["phases"]) == {
            "data_gen", "load_test", "stream_gen", "power_test",
            "throughput_1", "maintenance_1", "throughput_2",
            "maintenance_2"}
        # resumed run: NOTHING re-executes, identical metric
        calls.clear()
        m2 = run_full_bench(cfg, resume=True)
        assert calls == []
        assert m2["metric"] == m1["metric"]

    def test_resume_after_crash_reruns_only_the_tail(self, tmp_path,
                                                     monkeypatch):
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        m1 = run_full_bench(cfg)
        # simulate a crash during throughput round 2: drop it and
        # everything after from the journal
        jpath = os.path.join(cfg["paths"]["reports"],
                             "bench_state.json")
        state = json.load(open(jpath))
        for ph in ("throughput_2", "maintenance_2"):
            del state["phases"][ph]
        with open(jpath, "w") as f:
            json.dump(state, f)
        calls.clear()
        m2 = run_full_bench(cfg, resume=True)
        # load+power replayed from the journal (no transcode/power
        # subprocess), only the crashed tail re-ran
        assert "nds_tpu.nds.transcode" not in calls
        assert "nds_tpu.nds.power" not in calls
        assert calls.count("throughput") == 1
        assert calls.count("nds_tpu.nds.maintenance") == 1
        assert m2["metric"] == m1["metric"]

    def test_resume_refuses_config_drift(self, tmp_path, monkeypatch):
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        run_full_bench(cfg)
        cfg2 = dict(cfg)
        cfg2["scale_factor"] = 3000
        with pytest.raises(JournalMismatch):
            run_full_bench(cfg2, resume=True)

    def test_fresh_run_resets_stale_journal(self, tmp_path,
                                            monkeypatch):
        from nds_tpu.nds.bench import run_full_bench
        calls = []
        self._fake_phases(monkeypatch, calls)
        cfg = self._cfg(tmp_path)
        run_full_bench(cfg)
        n = len(calls)
        calls.clear()
        run_full_bench(cfg)  # NOT resume: everything re-runs
        assert len(calls) == n
