"""Host-staged plan splitting (engine/staging.py): correctness and
cache-refresh behavior with a forced-low STAGE_WEIGHT so even small
plans split. Full-size coverage comes from the single-device and
distributed differential tiers (q64/q72/q14...)."""

import numpy as np
import pytest

from nds_tpu.datagen import tpch
from nds_tpu.engine import staging
from nds_tpu.engine.device_exec import DeviceExecutor, make_device_factory
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h import streams
from nds_tpu.nds_h.schema import get_schemas

SF = 0.002


@pytest.fixture()
def raw():
    return {t: tpch.gen_table(t, SF) for t in get_schemas()}


def _sessions(raw, monkeypatch, weight=4):
    monkeypatch.setattr(DeviceExecutor, "STAGE_WEIGHT", weight)
    monkeypatch.setattr(staging, "MIN_CUT_WEIGHT", 2)
    schemas = get_schemas()
    cpu = Session.for_nds_h()
    dev = Session.for_nds_h(make_device_factory())
    for t in schemas:
        cpu.register_table(from_arrays(t, schemas[t], raw[t]))
        dev.register_table(from_arrays(t, schemas[t], raw[t]))
    return cpu, dev


def test_staged_matches_oracle_and_reports_bill(raw, monkeypatch):
    cpu, dev = _sessions(raw, monkeypatch)
    for qn in (3, 5, 10):
        sql = streams.render_query(qn)
        e = cpu.sql(sql)
        g = dev.sql(sql)
        assert list(g.to_pandas().iloc[:, 0]) == list(
            e.to_pandas().iloc[:, 0]), f"q{qn}"
        ex = dev._executor_factory(dev.tables)
        # the whole query's bill (sub programs included) is reported
        tm = ex.last_timings
        assert tm.get("staged_programs", 0) >= 1, f"q{qn} did not stage"
        assert tm["execute_ms"] > 0 and tm["bytes_scanned"] > 0


def test_repeat_run_reuses_stage_plans(raw, monkeypatch):
    cpu, dev = _sessions(raw, monkeypatch)
    sql = streams.render_query(3)
    first = dev.sql(sql).to_pandas()
    ex = dev._executor_factory(dev.tables)
    n_plans = len(ex._stage_plans)
    again = dev.sql(sql).to_pandas()
    assert len(ex._stage_plans) == n_plans  # cached split, no regrowth
    assert list(first.iloc[:, 0]) == list(again.iloc[:, 0])


def test_staged_temp_refreshes_after_base_table_dml(raw, monkeypatch):
    """A staged query re-run after data maintenance must see the new
    rows, not a stale intermediate. The session contract routes every
    mutation through invalidate() (engine/session.py:109); staged state
    must not survive it wrongly."""
    cpu, dev = _sessions(raw, monkeypatch)
    sql = streams.render_query(3)
    before = dev.sql(sql).to_pandas()
    # simulate data maintenance: drop every BUILDING customer, which
    # empties q3's result
    schemas = get_schemas()
    cust = dict(raw["customer"])
    keep = np.asarray(cust["c_mktsegment"]) != "BUILDING"
    cust = {k: np.asarray(v)[keep] for k, v in cust.items()}
    for s in (dev, cpu):
        s.register_table(from_arrays("customer", schemas["customer"],
                                     cust))
        s.invalidate()
    after = dev.sql(sql).to_pandas()
    exp = cpu.sql(sql).to_pandas()
    assert len(before) > 0
    assert len(after) == len(exp) == 0


def test_register_staged_fingerprint_refresh(raw, monkeypatch):
    """Executor-level guard (advisor r5 review): re-registering a temp
    with CHANGED content must drop the cached device buffers; identical
    content must keep them (warm bench path)."""
    schemas = get_schemas()
    ex = DeviceExecutor({t: from_arrays(t, schemas[t], raw[t])
                         for t in schemas})
    nation = ex.tables["nation"]
    ex._register_staged("__stage_t", nation)
    ex._buffers["__stage_t.n_nationkey"] = "sentinel"
    ex._register_staged("__stage_t", nation)        # same content
    assert ex._buffers["__stage_t.n_nationkey"] == "sentinel"
    trimmed = from_arrays("nation", schemas["nation"], {
        k: np.asarray(v)[:10] for k, v in raw["nation"].items()})
    ex._register_staged("__stage_t", trimmed)       # changed content
    assert "__stage_t.n_nationkey" not in ex._buffers
    assert ex.tables["__stage_t"] is trimmed


def test_cut_liveness_excludes_other_instances(raw, monkeypatch):
    """Bindings are not instance-unique: liveness must stage only what
    the cut's root exposes, never another scan instance's columns that
    happen to share a binding name (the q14 catalog_sales case)."""
    cpu, dev = _sessions(raw, monkeypatch, weight=8)
    # q18 scans lineitem twice (semijoin subquery + main); q21 thrice
    for qn in (18, 21):
        sql = streams.render_query(qn)
        e, g = cpu.sql(sql), dev.sql(sql)
        assert list(g.to_pandas().iloc[:, 0]) == list(
            e.to_pandas().iloc[:, 0]), f"q{qn}"
