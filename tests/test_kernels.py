"""Parity tests for the tensorized relational kernels
(nds_tpu/engine/kernels.py).

Two tiers, mirroring the repo's differential contract:

- SQL tier: purpose-built tables whose catalog stats make the planner
  pick each kernel (direct / matmul / partitioned / bitmask / minmax /
  segscan), every query cross-checked against the CPU oracle over all
  join kinds (inner/left/full/semi/anti), null join keys, duplicate
  keys, and empty (all-rows-filtered) inputs. Each test also asserts
  the intended kernel actually ENGAGED via the executor's trace-time
  kernel counts — a silently demoted kernel would otherwise pass
  parity while benchmarking the wrong code.
- Unit tier: each kernel function against a numpy brute-force oracle,
  including the overflow accounting of the partitioned join, plus one
  fixed-seed fuzz case per kernel.
"""

import numpy as np
import pandas as pd
import pytest

from nds_tpu.engine import kernels as KX
from nds_tpu.engine.device_exec import make_device_factory
from nds_tpu.engine.session import Session
from nds_tpu.engine.types import INT32, INT64, Schema, varchar
from nds_tpu.io.host_table import from_arrays
from nds_tpu.sql.planner import CatalogInfo

from tests.test_device_engine import assert_frames_close

NF = 400     # fact rows
ND = 120     # dim rows (> MATMUL_MAX_BUILD -> direct)
NT = 8       # tiny dim rows (<= MATMUL_MAX_BUILD -> matmul)


def _catalog():
    fact = Schema.of(
        ("f_id", INT32, False), ("f_dim", INT32, True),
        ("f_tiny", INT32, False), ("f_key", INT32, False),
        ("f_val", INT32, True), ("f_qty", INT32, False))
    fact2 = Schema.of(
        ("g_key", INT32, False), ("g_val", INT32, True),
        ("g_qty", INT32, False))
    dim = Schema.of(("d_id", INT32, False),
                    ("d_name", varchar(10), False))
    tiny = Schema.of(("t_id", INT32, False),
                     ("t_name", varchar(10), False))
    return CatalogInfo(
        {"fact": fact, "fact2": fact2, "dim": dim, "tiny": tiny},
        {"dim": ["d_id"], "tiny": ["t_id"], "fact": ["f_id"]},
        {"fact": NF, "fact2": NF, "dim": ND, "tiny": NT})


def _data():
    rng = np.random.default_rng(20260803)
    dim_valid = rng.random(NF) >= 0.1      # ~10% NULL join keys
    names = np.array(["alpha", "beta", "gamma", "delta"], dtype=object)
    fact = {
        "f_id": np.arange(NF, dtype=np.int32),
        # duplicate keys by construction; some keys miss the dim
        # domain entirely (d_id stops at ND-1, f_dim reaches ND+4)
        "f_dim": rng.integers(0, ND + 5, NF).astype(np.int32),
        "f_dim#null": dim_valid,
        "f_tiny": rng.integers(0, NT, NF).astype(np.int32),
        "f_key": rng.integers(0, NF // 4, NF).astype(np.int32),
        "f_val": rng.integers(0, 10, NF).astype(np.int32),
        "f_val#null": rng.random(NF) >= 0.1,
        "f_qty": rng.integers(1, 100, NF).astype(np.int32),
    }
    fact2 = {
        "g_key": rng.integers(0, NF // 4, NF).astype(np.int32),
        "g_val": rng.integers(0, 10, NF).astype(np.int32),
        "g_val#null": rng.random(NF) >= 0.1,
        "g_qty": rng.integers(1, 100, NF).astype(np.int32),
    }
    dim = {
        "d_id": np.arange(ND, dtype=np.int32),
        "d_name": names[rng.integers(0, 4, ND)],
    }
    tiny = {
        "t_id": np.arange(NT, dtype=np.int32),
        "t_name": names[rng.integers(0, 4, NT)],
    }
    return {"fact": fact, "fact2": fact2, "dim": dim, "tiny": tiny}


def _build_sessions():
    cat = _catalog()
    data = _data()

    def build(factory=None):
        s = Session(cat, factory)
        for t in cat.schemas:
            s.register_table(from_arrays(t, cat.schemas[t], data[t]))
        return s

    return build(), build(make_device_factory())


@pytest.fixture(scope="module")
def sessions():
    return _build_sessions()


def both(sessions, sql, want_kernel=None):
    """CPU-oracle vs device differential + kernel-engagement check."""
    cpu, dev = sessions
    exp = cpu.sql(sql).to_pandas()
    got = dev.sql(sql).to_pandas()
    assert_frames_close(got, exp, sql[:48])
    if want_kernel is not None:
        ex = dev._executor_factory(dev.tables)
        kern = ex.last_timings.get("__kernels") or {}
        assert kern.get(want_kernel), (
            f"expected kernel {want_kernel!r} to engage, trace counted "
            f"{kern!r} for {sql[:60]!r}")
    return exp


# ------------------------------------------------------- SQL tier: joins

def test_inner_join_direct(sessions):
    both(sessions,
         "select f_id, d_name from fact join dim on f_dim = d_id "
         "order by f_id",
         want_kernel="join.direct")


def test_left_join_direct_keeps_unmatched(sessions):
    # rows with NULL f_dim or f_dim >= ND survive with NULL d_name
    exp = both(sessions,
               "select f_id, d_name from fact left join dim "
               "on f_dim = d_id order by f_id",
               want_kernel="join.direct")
    assert exp["d_name"].isna().any()


def test_inner_join_matmul_tiny_build(sessions):
    both(sessions,
         "select f_id, t_name from fact join tiny on f_tiny = t_id "
         "order by f_id",
         want_kernel="join.matmul")


def test_full_outer_join(sessions):
    # FULL OUTER needs unique keys both sides: join grouped CTEs
    both(sessions,
         "with a as (select f_dim k, count(*) ca from fact group by "
         "f_dim), b as (select d_id k, count(*) cb from dim group by "
         "d_id) select a.k ak, b.k bk, ca, cb from a full outer join "
         "b on a.k = b.k order by ak, bk")


def test_semi_join_bitmask(sessions):
    both(sessions,
         "select f_id from fact where exists (select 1 from dim "
         "where d_id = f_dim) order by f_id",
         want_kernel="semi.bitmask")


def test_anti_join_bitmask(sessions):
    both(sessions,
         "select f_id from fact where not exists (select 1 from dim "
         "where d_id = f_dim) order by f_id",
         want_kernel="semi.bitmask")


def test_exists_residual_minmax(sessions):
    # the q21 shape: exists a row with the same key and a DIFFERENT
    # value -> dense per-key min/max tables
    both(sessions,
         "select f_id from fact where exists (select 1 from fact2 "
         "where g_key = f_key and g_val <> f_val) order by f_id",
         want_kernel="semi.minmax")


def test_not_exists_residual_minmax(sessions):
    both(sessions,
         "select f_id from fact where not exists (select 1 from fact2 "
         "where g_key = f_key and g_val <> f_val) order by f_id",
         want_kernel="semi.minmax")


def test_mn_join_partitioned(monkeypatch):
    # the radix-partitioned path only engages for large estimates:
    # shrink the threshold and plan fresh sessions so annotate() sees it
    monkeypatch.setattr(KX, "PARTITION_MIN_ROWS", 64)
    cpu, dev = _build_sessions()
    sql = ("select f_id, g_qty from fact join fact2 on f_key = g_key "
           "order by f_id, g_qty")
    exp = cpu.sql(sql).to_pandas()
    got = dev.sql(sql).to_pandas()
    assert_frames_close(got, exp, "mn-partitioned")
    ex = dev._executor_factory(dev.tables)
    kern = ex.last_timings.get("__kernels") or {}
    assert kern.get("join.partitioned"), kern


def test_empty_probe_side(sessions):
    # all probe rows filtered out: every kernel must survive a fully
    # masked input (static shapes keep the capacity, validity is 0)
    for sql in (
            "select f_id, d_name from fact join dim on f_dim = d_id "
            "where f_id < 0",
            "select f_id from fact where f_id < 0 and exists "
            "(select 1 from dim where d_id = f_dim)"):
        cpu, dev = sessions
        exp = cpu.sql(sql).to_pandas()
        got = dev.sql(sql).to_pandas()
        assert len(got) == 0 and len(exp) == 0


def test_empty_build_side(sessions):
    both(sessions,
         "with d as (select d_id from dim where d_id < 0) "
         "select f_id from fact where exists (select 1 from d "
         "where d_id = f_dim) order by f_id")


# ------------------------------------------- SQL tier: aggregation/window

def test_grouped_minmax_segscan(sessions):
    both(sessions,
         "select f_key, min(f_val) mn, max(f_val) mx, sum(f_qty) s, "
         "count(*) c from fact group by f_key order by f_key",
         want_kernel="agg.segscan")


def test_grouped_minmax_null_groups(sessions):
    # NULL group key forms its own group; NULL values are skipped
    both(sessions,
         "select f_dim, min(f_val) mn, max(f_val) mx from fact "
         "group by f_dim order by f_dim",
         want_kernel="agg.segscan")


def test_window_partition_minmax(sessions):
    both(sessions,
         "select f_id, min(f_qty) over (partition by f_key) pmn, "
         "max(f_qty) over (partition by f_key) pmx from fact "
         "order by f_id")


def test_kernels_env_kill_switch(monkeypatch):
    # NDS_TPU_KERNELS=0 plans everything unannotated: the legacy sort
    # paths serve the same rows
    monkeypatch.setenv("NDS_TPU_KERNELS", "0")
    cpu, dev = _build_sessions()
    sql = ("select f_id, d_name from fact join dim on f_dim = d_id "
           "order by f_id")
    exp = cpu.sql(sql).to_pandas()
    got = dev.sql(sql).to_pandas()
    assert_frames_close(got, exp, "kill-switch")
    ex = dev._executor_factory(dev.tables)
    kern = ex.last_timings.get("__kernels") or {}
    assert not kern.get("join.direct"), kern
    assert kern.get("join.sortmerge") or kern.get("join.presorted"), kern


# ------------------------------------------------- unit tier: primitives

def _jnp():
    import jax.numpy as jnp
    return jnp


def test_direct_lookup_join_unit():
    jnp = _jnp()
    rng = np.random.default_rng(7)
    dom = 32
    bkey = np.array([3, 9, 11, 4, 0, 31], dtype=np.int32)
    bok = np.array([True, True, False, True, True, True])
    pkey = rng.integers(-2, dom + 2, 64).astype(np.int32)
    pok = rng.random(64) >= 0.2
    ridx, hit = KX.direct_lookup_join(
        jnp.asarray(bkey), jnp.asarray(bok),
        jnp.asarray(pkey), jnp.asarray(pok), 0, dom)
    ridx, hit = np.asarray(ridx), np.asarray(hit)
    valid = {int(k): i for i, k in enumerate(bkey) if bok[i]}
    for j in range(64):
        exp_hit = bool(pok[j]) and int(pkey[j]) in valid
        assert bool(hit[j]) == exp_hit, j
        if exp_hit:
            assert int(ridx[j]) == valid[int(pkey[j])]
        assert 0 <= int(ridx[j]) < len(bkey)  # clamped even on miss


def test_matmul_probe_join_unit():
    jnp = _jnp()
    rng = np.random.default_rng(8)
    bkey = np.array([5, 2, 19, 7], dtype=np.int32)
    bok = np.array([True, False, True, True])
    pkey = rng.integers(0, 24, 50).astype(np.int32)
    pok = rng.random(50) >= 0.1
    ridx, hit = KX.matmul_probe_join(
        jnp.asarray(bkey), jnp.asarray(bok),
        jnp.asarray(pkey), jnp.asarray(pok))
    ridx, hit = np.asarray(ridx), np.asarray(hit)
    valid = {int(k): i for i, k in enumerate(bkey) if bok[i]}
    for j in range(50):
        exp_hit = bool(pok[j]) and int(pkey[j]) in valid
        assert bool(hit[j]) == exp_hit, j
        if exp_hit:
            assert int(ridx[j]) == valid[int(pkey[j])]


def test_bitmask_semi_unit():
    jnp = _jnp()
    rng = np.random.default_rng(9)
    dom = 40
    bkey = rng.integers(0, dom, 30).astype(np.int32)
    bok = rng.random(30) >= 0.3
    pkey = rng.integers(-3, dom + 3, 80).astype(np.int32)
    pok = rng.random(80) >= 0.2
    member = np.asarray(KX.bitmask_semi(
        jnp.asarray(bkey), jnp.asarray(bok),
        jnp.asarray(pkey), jnp.asarray(pok), 0, dom))
    present = set(int(k) for i, k in enumerate(bkey) if bok[i])
    for j in range(80):
        assert bool(member[j]) == (bool(pok[j])
                                   and int(pkey[j]) in present), j


def test_keyed_minmax_semi_unit():
    jnp = _jnp()
    rng = np.random.default_rng(10)
    dom = 16
    bkey = rng.integers(0, dom, 60).astype(np.int32)
    bok = rng.random(60) >= 0.2
    bval = rng.integers(0, 4, 60).astype(np.int32)
    pkey = rng.integers(0, dom, 60).astype(np.int32)
    pok = rng.random(60) >= 0.2
    pval = rng.integers(0, 4, 60).astype(np.int32)
    got = np.asarray(KX.keyed_minmax_semi(
        jnp.asarray(bkey), jnp.asarray(bok), jnp.asarray(bval),
        jnp.asarray(pkey), jnp.asarray(pok), jnp.asarray(pval),
        0, dom))
    for j in range(60):
        exp = bool(pok[j]) and any(
            bok[i] and int(bkey[i]) == int(pkey[j])
            and int(bval[i]) != int(pval[j]) for i in range(60))
        assert bool(got[j]) == exp, j


def _pairs(lidx, ridx, present, lkey, rkey):
    li, ri = np.asarray(lidx)[np.asarray(present)], \
        np.asarray(ridx)[np.asarray(present)]
    assert (np.asarray(lkey)[li] == np.asarray(rkey)[ri]).all()
    return sorted(zip(li.tolist(), ri.tolist()))


def test_partitioned_mn_join_unit():
    jnp = _jnp()
    rng = np.random.default_rng(11)
    n = 200
    lkey = rng.integers(0, 40, n).astype(np.int32)
    rkey = rng.integers(0, 40, n).astype(np.int32)
    lok = rng.random(n) >= 0.1
    rok = rng.random(n) >= 0.1
    exp = sorted(
        (i, j) for i in range(n) for j in range(n)
        if lok[i] and rok[j] and lkey[i] == rkey[j])
    K = 4 * len(exp) + 16
    lidx, ridx, present, over = KX.partitioned_mn_join(
        jnp.asarray(lkey), jnp.asarray(lok),
        jnp.asarray(rkey), jnp.asarray(rok), K, 2.0)
    assert int(over) == 0
    assert _pairs(lidx, ridx, present, lkey, rkey) == exp


def test_partitioned_mn_join_overflow_counted():
    jnp = _jnp()
    n = 64
    lkey = np.zeros(n, dtype=np.int32)   # one key, n*n pairs
    rkey = np.zeros(n, dtype=np.int32)
    ok = np.ones(n, dtype=bool)
    K = 16  # far below n*n
    _l, _r, present, over = KX.partitioned_mn_join(
        jnp.asarray(lkey), jnp.asarray(ok),
        jnp.asarray(rkey), jnp.asarray(ok), K, 2.0)
    # capacity misses must be COUNTED, not silently dropped (the
    # executor's doubled-slack retry keys off this)
    assert int(over) > 0
    assert int(np.asarray(present).sum()) <= K


def test_partitioned_mn_join_empty_sides():
    jnp = _jnp()
    n = 32
    key = np.arange(n, dtype=np.int32)
    none = np.zeros(n, dtype=bool)
    ok = np.ones(n, dtype=bool)
    _l, _r, present, over = KX.partitioned_mn_join(
        jnp.asarray(key), jnp.asarray(none),
        jnp.asarray(key), jnp.asarray(ok), 64, 2.0)
    assert int(over) == 0
    assert int(np.asarray(present).sum()) == 0


def test_seg_reduce_at_ends_unit():
    jnp = _jnp()
    rng = np.random.default_rng(12)
    n, G = 100, 12
    gid = np.sort(rng.integers(0, G, n)).astype(np.int32)
    data = rng.integers(0, 1000, n).astype(np.int32)
    starts = np.searchsorted(gid, np.arange(G)).astype(np.int32)
    got = np.asarray(KX.seg_reduce_at_ends(
        jnp.minimum, jnp.asarray(data), jnp.asarray(gid),
        jnp.asarray(starts)))
    for g in range(G):
        rows = data[gid == g]
        if len(rows):
            assert got[g] == rows.min(), g


def test_last_of_group_unit():
    jnp = _jnp()
    change = np.array([True, False, False, True, True, False])
    got = np.asarray(KX.last_of_group(jnp.asarray(change), 6))
    np.testing.assert_array_equal(got, [2, 2, 2, 3, 5, 5])


def test_domain_and_feasibility_rules():
    assert KX.domain_of(0, 99) == 100
    assert KX.domain_of(None, 5) is None
    assert KX.domain_of(5, 4) is None                  # empty range
    assert KX.domain_of(0, KX.DIRECT_MAX_DOMAIN) is None  # too wide
    assert KX.direct_feasible(100, 10)                 # 100 <= 10*16
    assert not KX.direct_feasible(1000, 10)
    assert not KX.direct_feasible(None, 10)


def test_select_join_kernel_rules():
    assert KX.select_join_kernel(1e6, 10, True, "inner") == KX.JOIN_MATMUL
    assert KX.select_join_kernel(1e6, 1e4, True, "inner") == KX.JOIN_DIRECT
    assert KX.select_join_kernel(1e6, 1e6, False, "inner") \
        == KX.JOIN_PARTITIONED
    assert KX.select_join_kernel(100, 100, False, "inner") == KX.JOIN_SORT
    assert KX.select_join_kernel(1e6, 1e6, False, "left") == KX.JOIN_SORT


# --------------------------------------------------- unit tier: fuzzing

@pytest.mark.parametrize("seed", [101, 202, 303])
def test_fuzz_direct_vs_sortmerge_semantics(seed):
    """Fixed-seed fuzz: direct lookup == brute-force dict join on
    random domains, null patterns, and duplicate probe keys."""
    jnp = _jnp()
    rng = np.random.default_rng(seed)
    dom = int(rng.integers(4, 200))
    nb = int(rng.integers(1, dom + 1))
    n = int(rng.integers(1, 500))
    bkey = rng.permutation(dom)[:nb].astype(np.int32)
    bok = rng.random(nb) >= 0.2
    pkey = rng.integers(-2, dom + 2, n).astype(np.int32)
    pok = rng.random(n) >= 0.2
    ridx, hit = KX.direct_lookup_join(
        jnp.asarray(bkey), jnp.asarray(bok),
        jnp.asarray(pkey), jnp.asarray(pok), 0, dom)
    valid = {int(k): i for i, k in enumerate(bkey) if bok[i]}
    hit = np.asarray(hit)
    ridx = np.asarray(ridx)
    for j in range(n):
        exp = bool(pok[j]) and int(pkey[j]) in valid
        assert bool(hit[j]) == exp
        if exp:
            assert int(ridx[j]) == valid[int(pkey[j])]


@pytest.mark.parametrize("seed", [404, 505])
def test_fuzz_partitioned_pairs(seed):
    jnp = _jnp()
    rng = np.random.default_rng(seed)
    n = int(rng.integers(20, 150))
    nk = int(rng.integers(2, 30))
    lkey = rng.integers(0, nk, n).astype(np.int32)
    rkey = rng.integers(0, nk, n).astype(np.int32)
    lok = rng.random(n) >= 0.15
    rok = rng.random(n) >= 0.15
    exp = sorted(
        (i, j) for i in range(n) for j in range(n)
        if lok[i] and rok[j] and lkey[i] == rkey[j])
    K = 4 * max(len(exp), 1) + 32
    lidx, ridx, present, over = KX.partitioned_mn_join(
        jnp.asarray(lkey), jnp.asarray(lok),
        jnp.asarray(rkey), jnp.asarray(rok), K, 3.0)
    assert int(over) == 0
    assert _pairs(lidx, ridx, present, lkey, rkey) == exp


@pytest.mark.parametrize("seed", [606, 707])
def test_fuzz_sql_join_agg(seed):
    """Fixed-seed SQL fuzz across the kernel set: random tables,
    CPU-oracle differential on a join+agg+semi query battery."""
    rng = np.random.default_rng(seed)
    nf, nd = int(rng.integers(50, 300)), int(rng.integers(3, 60))
    fact = Schema.of(("a_id", INT32, False), ("a_k", INT32, True),
                     ("a_v", INT32, False))
    dim = Schema.of(("b_k", INT32, False), ("b_w", INT32, False))
    cat = CatalogInfo({"a": fact, "b": dim}, {"b": ["b_k"]},
                      {"a": nf, "b": nd})
    a = {"a_id": np.arange(nf, dtype=np.int32),
         "a_k": rng.integers(0, nd + 2, nf).astype(np.int32),
         "a_k#null": rng.random(nf) >= 0.15,
         "a_v": rng.integers(0, 1000, nf).astype(np.int32)}
    b = {"b_k": np.arange(nd, dtype=np.int32),
         "b_w": rng.integers(0, 100, nd).astype(np.int32)}

    def build(factory=None):
        s = Session(cat, factory)
        s.register_table(from_arrays("a", fact, a))
        s.register_table(from_arrays("b", dim, b))
        return s

    cpu, dev = build(), build(make_device_factory())
    for sql in (
            "select a_id, b_w from a join b on a_k = b_k order by a_id",
            "select a_id, b_w from a left join b on a_k = b_k "
            "order by a_id",
            "select a_k, min(a_v) mn, max(a_v) mx, count(*) c from a "
            "group by a_k order by a_k",
            "select a_id from a where exists (select 1 from b where "
            "b_k = a_k) order by a_id",
            "select a_id from a where not exists (select 1 from b "
            "where b_k = a_k) order by a_id"):
        exp = cpu.sql(sql).to_pandas()
        got = dev.sql(sql).to_pandas()
        assert_frames_close(got, exp, f"fuzz{seed}:{sql[:40]}")
