"""Data-maintenance tests: DML engine support (INSERT/DELETE), the 11
LF_*/DF_* refresh functions end-to-end against a versioned warehouse,
DATE1/DATE2 substitution, snapshot commit and rollback — the vertical
slice of `nds/nds_maintenance.py` + `nds_rollback.py`."""

import os

import numpy as np
import pytest

from nds_tpu.columnar import delta
from nds_tpu.datagen import tpcds
from nds_tpu.engine.session import Session
from nds_tpu.io.host_table import from_arrays
from nds_tpu.io.snapshots import SnapshotLog
from nds_tpu.nds import gen_data, maintenance, transcode
from nds_tpu.nds.schema import get_schemas

SF = 0.01


def _nrows(sess, table):
    """Logical row count: DELETEs land as delta bitmasks, so
    ``table.nrows`` stays physical and the visible count subtracts the
    masked rows."""
    return delta.visible_rows(sess.tables[table])


def _session(tables=("store_sales", "store_returns", "date_dim",
                     "reason")):
    schemas = get_schemas()
    sess = Session.for_nds()
    for t in tables:
        sess.register_table(
            from_arrays(t, schemas[t], tpcds.gen_table(t, SF)))
    return sess


class TestDml:
    def test_insert_select(self):
        sess = _session()
        n0 = sess.tables["store_sales"].nrows
        r = sess.sql("select count(*) as c from store_sales "
                     "where ss_quantity > 95")
        expected = int(r.cols[0][0])
        out = sess.sql("insert into store_sales (select * from "
                       "store_sales where ss_quantity > 95)")
        assert out is None
        assert sess.tables["store_sales"].nrows == n0 + expected

    def test_insert_preserves_null_masks(self):
        sess = _session()
        col0 = sess.tables["store_sales"].column("ss_customer_sk")
        nulls0 = int((~col0.null_mask).sum())
        sess.sql("insert into store_sales "
                 "(select * from store_sales)")
        col1 = sess.tables["store_sales"].column("ss_customer_sk")
        assert int((~col1.null_mask).sum()) == 2 * nulls0

    def test_delete_scalar_subquery_range(self):
        sess = _session()
        n0 = sess.tables["store_sales"].nrows
        r = sess.sql(
            "select count(*) as c from store_sales where "
            "ss_sold_date_sk >= 2450815 and ss_sold_date_sk <= 2450845")
        in_window = int(r.cols[0][0])
        assert in_window > 0
        sess.sql(
            "delete from store_sales where ss_sold_date_sk >= "
            "(select min(d_date_sk) from date_dim where d_date between "
            "'1998-01-01' and '1998-01-31') and ss_sold_date_sk <= "
            "(select max(d_date_sk) from date_dim where d_date between "
            "'1998-01-01' and '1998-01-31')")
        assert _nrows(sess, "store_sales") == n0 - in_window

    def test_delete_null_dates_survive(self):
        """SQL DELETE keeps rows where the predicate is NULL — the
        nullable ss_sold_date_sk FK must never be deleted by a date
        range (3-valued logic, unlike a complemented filter)."""
        sess = _session()
        col = sess.tables["store_sales"].column("ss_sold_date_sk")
        n_null = int((~col.null_mask).sum())
        assert n_null > 0
        sess.sql("delete from store_sales where ss_sold_date_sk >= 0")
        tbl = sess.tables["store_sales"]
        assert _nrows(sess, "store_sales") == n_null
        # every surviving (live) row has a NULL date
        live = delta.live_mask(tbl)
        col2 = tbl.column("ss_sold_date_sk")
        assert col2.null_mask is not None
        surviving_valid = col2.null_mask if live is None \
            else col2.null_mask[live]
        assert not surviving_valid.any()

    def test_delete_in_subquery(self):
        sess = _session()
        r = sess.sql(
            "select count(*) as c from store_returns where "
            "sr_ticket_number in (select distinct ss_ticket_number from "
            "store_sales, date_dim where ss_sold_date_sk=d_date_sk and "
            "d_date between '1998-02-01' and '1998-03-01')")
        expected = int(r.cols[0][0])
        n0 = sess.tables["store_returns"].nrows
        sess.sql(
            "delete from store_returns where sr_ticket_number in "
            "(select distinct ss_ticket_number from store_sales, "
            "date_dim where ss_sold_date_sk=d_date_sk and d_date "
            "between '1998-02-01' and '1998-03-01')")
        assert _nrows(sess, "store_returns") == n0 - expected

    def test_dml_invalidates_plan_cache(self):
        sess = _session()
        q = "select count(*) as c from store_sales"
        before = int(sess.sql(q).cols[0][0])
        sess.sql("delete from store_sales where ss_quantity > 0")
        after = int(sess.sql(q).cols[0][0])
        assert after < before

    def test_drop_view_requires_existence(self):
        sess = _session()
        sess.sql("drop view if exists nope")  # silent
        with pytest.raises(ValueError):
            sess.sql("drop view nope")

    def test_delete_decimal_literal_coercion(self):
        """WHERE money_col > 100 means $100, not 100 scaled cents."""
        sess = _session()
        r = sess.sql("select count(*) as c from store_sales "
                     "where ss_sales_price > 50.00")
        over_50_dollars = int(r.cols[0][0])
        n0 = sess.tables["store_sales"].nrows
        sess.sql("delete from store_sales where ss_sales_price > 50.00")
        assert _nrows(sess, "store_sales") == n0 - over_50_dollars

    def test_delete_date_string_literal_coercion(self):
        sess = _session(("date_dim",))
        n0 = sess.tables["date_dim"].nrows
        r = sess.sql("select count(*) as c from date_dim "
                     "where d_date >= '2000-01-01'")
        after = int(r.cols[0][0])
        sess.sql("delete from date_dim where d_date >= '2000-01-01'")
        assert _nrows(sess, "date_dim") == n0 - after

    def test_insert_rejects_trailing_statement(self):
        sess = _session()
        with pytest.raises(Exception, match="trailing"):
            sess.sql("insert into store_sales (select * from "
                     "store_sales); delete from store_sales")


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    root = tmp_path_factory.mktemp("maint")
    raw = str(root / "raw")
    wh = str(root / "wh")
    refresh = str(root / "refresh1")
    gen_data.generate_data_local(SF, 1, raw, workers=1)
    transcode.transcode(raw, wh, str(root / "load.txt"))
    gen_data.generate_refresh_data(SF, 1, refresh)
    return {"wh": wh, "refresh": refresh, "root": str(root)}


class TestMaintenanceRun:
    def test_full_maintenance_and_rollback(self, warehouse, tmp_path):
        from nds_tpu.nds.power import SUITE
        from nds_tpu.utils import power_core
        from nds_tpu.utils.config import EngineConfig

        cfg = EngineConfig(overrides={"engine.backend": "cpu"})

        def fact_counts():
            sess = power_core.make_session(SUITE, cfg)
            power_core.load_warehouse(
                SUITE, sess, warehouse["wh"],
                tables=maintenance.MUTABLE_TABLES)
            return {t: delta.visible_rows(sess.tables[t])
                    for t in maintenance.MUTABLE_TABLES}

        before = fact_counts()
        failures = maintenance.run_maintenance(
            warehouse["wh"], warehouse["refresh"],
            str(tmp_path / "dm.csv"), config=cfg,
            json_summary_folder=str(tmp_path / "json"))
        assert failures == 0
        after = fact_counts()
        # every channel changed: inserts extend history past the base
        # window, deletes remove a base window
        assert after != before
        # the delete windows are inside base history and the refresh
        # sets are small, so deletes dominate
        assert after["store_sales"] != before["store_sales"]
        assert after["inventory"] < before["inventory"]
        # inserted rows reference resolvable dimension SKs
        sess = power_core.make_session(SUITE, cfg)
        power_core.load_warehouse(SUITE, sess, warehouse["wh"],
                                  tables=["store_sales"])
        tn = sess.tables["store_sales"].column("ss_ticket_number").values
        assert (tn >= 1_000_000_000).any()
        # time log carries the Tdm row the orchestrator reads
        rows = open(str(tmp_path / "dm.csv")).read()
        assert "Data Maintenance Time" in rows
        # rollback restores the baseline
        from nds_tpu.nds.rollback import rollback
        rollback(warehouse["wh"], 0.0)
        assert fact_counts() == before

    def test_snapshot_log_versions(self, tmp_path):
        wh = str(tmp_path / "wh")
        os.makedirs(os.path.join(wh, "t1"))
        # fake baseline parquet
        import pyarrow as pa
        import pyarrow.parquet as pq
        pq.write_table(pa.table({"a": [1, 2]}),
                       os.path.join(wh, "t1", "part-0.parquet"))
        log = SnapshotLog(wh)
        v1dir = log.version_dir("t1", 1)
        pq.write_table(pa.table({"a": [1, 2, 3]}),
                       os.path.join(v1dir, "part-0.parquet"))
        log.commit({"t1": [os.path.relpath(
            os.path.join(v1dir, "part-0.parquet"), wh)]})
        cur = SnapshotLog(wh).current(["t1"])
        assert "_v1" in cur["t1"][0]
        SnapshotLog(wh).rollback_to_timestamp(0.0)
        cur = SnapshotLog(wh).current(["t1"])
        assert "_v1" not in cur["t1"][0]

    def test_date_substitution(self):
        sql = "where d_date between 'DATE1' and 'DATE2'"
        out = maintenance.replace_date(sql, "1998-01-01", "1998-01-31")
        assert "'1998-01-01'" in out and "DATE1" not in out

    def test_all_eleven_functions_ship(self):
        qs = maintenance.get_maintenance_queries(
            maintenance.INSERT_FUNCS + maintenance.DELETE_FUNCS
            + maintenance.INVENTORY_DELETE_FUNCS)
        assert len(qs) == 11
        for name, sql in qs.items():
            stmts = maintenance.statements(sql)
            assert stmts, name
            if name.startswith("LF_"):
                assert any("insert into" in s.lower() for s in stmts)
            else:
                assert any("delete from" in s.lower() for s in stmts)


def test_maintenance_functions_on_device_engine():
    """The LF_*/DF_* refresh SQL also runs through the TPU device
    engine (INSERT's SELECT executes on-device; DML mutation stays
    host-side and invalidates the executor)."""
    from nds_tpu.datagen import tpcds_refresh
    from nds_tpu.engine.device_exec import make_device_factory
    from nds_tpu.nds.schema import get_maintenance_schemas

    schemas = get_schemas()
    msch = get_maintenance_schemas()
    sess = Session.for_nds(make_device_factory(),
                           include_maintenance=True)
    for t in ("store_sales", "store_returns", "date_dim", "item",
              "customer", "store", "promotion", "time_dim", "reason"):
        sess.register_table(
            from_arrays(t, schemas[t], tpcds.gen_table(t, SF)))
    for t in ("s_purchase", "s_purchase_lineitem", "delete",
              "inventory_delete"):
        sess.register_table(from_arrays(
            t, msch[t], tpcds_refresh.gen_refresh_table(t, SF, 1)))
    n0 = sess.tables["store_sales"].nrows
    d1, d2, _i1, _i2 = maintenance.get_delete_date(sess)
    qs = maintenance.get_maintenance_queries(["LF_SS", "DF_SS"])
    maintenance.run_dm_query(sess, qs["LF_SS"])
    n1 = sess.tables["store_sales"].nrows
    assert n1 > n0, "device-engine LF_SS must insert rows"
    maintenance.run_dm_query(
        sess, maintenance.replace_date(qs["DF_SS"], d1, d2))
    assert delta.visible_rows(sess.tables["store_sales"]) < n1


@pytest.mark.slow
class TestDistributedBackend:
    """Maintenance + throughput drives through the `distributed` backend
    (VERDICT r3 "next" #7): DML and concurrent streams must work over
    the mesh executor, not only single-device."""

    def test_maintenance_distributed_backend(self, warehouse, tmp_path):
        from nds_tpu.utils.config import EngineConfig

        cfg = EngineConfig(overrides={"engine.backend": "distributed"})
        failures = maintenance.run_maintenance(
            warehouse["wh"], warehouse["refresh"],
            str(tmp_path / "dm_dist.csv"), config=cfg,
            commit=False)  # no_commit: the cpu test owns the warehouse
        assert failures == 0
        from nds_tpu.utils.timelog import TimeLog
        rows = {q: ms for _a, q, ms in TimeLog.read(
            str(tmp_path / "dm_dist.csv"))}
        assert "Data Maintenance Time" in rows
        assert sum(1 for q in rows if q.startswith(("LF_", "DF_"))) == 11

    def test_throughput_distributed_backend(self, warehouse, tmp_path,
                                            monkeypatch):
        from nds_tpu.nds.streams import generate_query_streams
        from nds_tpu.nds.throughput import run_streams

        # stream subprocesses re-run interpreter startup, where the
        # deployment sitecustomize can re-pin jax to the remote TPU
        # plugin; NDS_TPU_PLATFORM wins (device_exec import contract)
        monkeypatch.setenv("NDS_TPU_PLATFORM", "cpu")

        sdir = tmp_path / "streams"
        generate_query_streams(str(sdir), 3, rng_seed=11)  # query_0..2
        # truncate each stream to its first 3 queries: the test is the
        # concurrent distributed drive, not 99-query latency
        short = []
        for i in (1, 2):
            txt = (sdir / f"query_{i}.sql").read_text()
            parts = txt.split("-- start query")
            cut = "-- start query".join(parts[:4])
            p = tmp_path / f"short_{i}.sql"
            p.write_text(cut)
            short.append(str(p))
        elapse, codes = run_streams(
            warehouse["wh"], short, str(tmp_path / "tp"),
            backend="distributed")
        assert codes == [0, 0]
        assert elapse > 0
        logs = sorted(os.listdir(tmp_path / "tp"))
        assert [f for f in logs if f.endswith("_time.csv")], logs
