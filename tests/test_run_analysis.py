"""Run-analysis layer tests (nds_tpu/obs/analyze.py + friends): the
attribution-sums-to-wall-clock invariant on a REAL 3-query CPU power
run, the noise-aware diff gate on the committed golden run-dirs
(regression / improvement / noise / added / removed), HTML report
smoke-parse, per-query memory HWM monotonicity + reset, the live
snapshot emitter's OpenMetrics validity, the BenchReport summary
schema gate, and the tracer's abnormal-exit flush."""

import html.parser
import json
import os
import time

import pytest

from nds_tpu.obs import analyze, memwatch
from nds_tpu.obs import metrics as obs_metrics
from nds_tpu.obs.snapshot import (
    MetricsSnapshotter, om_path_for, parse_spec, to_openmetrics,
    validate_openmetrics,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")
RUN_A = os.path.join(FIXTURES, "run_a")
RUN_B = os.path.join(FIXTURES, "run_b")


# ------------------------------------------------- attribution (units)

class TestAttribution:
    def test_categories_plus_residual_sum_exactly(self):
        for run in (RUN_A, RUN_B):
            a = analyze.analyze_run(run)
            for row in a["queries"]:
                total = (sum(row["categories"].values())
                         + row["residual_ms"])
                assert total == pytest.approx(row["wall_ms"], abs=1e-9)

    def test_unmapped_self_time_bills_nearest_ancestor(self):
        # a child with an unmapped name inside stage.sub bills its
        # self-time to host_staging, not to residual
        summary = {
            "query": "q", "queryStatus": ["Completed"],
            "queryTimes": [100], "startTime": 1,
            "spans": {"name": "query", "dur_ms": 95.0, "children": [
                {"name": "stage.sub", "dur_ms": 40.0, "children": [
                    {"name": "device.execute", "dur_ms": 30.0,
                     "children": [
                         {"name": "device.run", "dur_ms": 25.0,
                          "children": []}]}]}]},
        }
        row = analyze.attribute_query(summary)
        cats = row["categories"]
        # stage.sub self 10 + device.execute self 5 -> host_staging
        assert cats["host_staging"] == pytest.approx(15.0)
        assert cats["execute"] == pytest.approx(25.0)
        # query self-time (95-40=55) has no categorized ancestor
        assert row["residual_ms"] == pytest.approx(100 - 40.0)

    def test_retry_backoff_is_its_own_category(self):
        summary = {"query": "q", "queryStatus": ["Completed"],
                   "queryTimes": [1000], "startTime": 1,
                   "retry_backoff_s": 0.25}
        row = analyze.attribute_query(summary)
        assert row["categories"]["retry_backoff"] == pytest.approx(250.0)
        assert row["residual_ms"] == pytest.approx(750.0)

    def test_dedupe_suffixes_by_wall_rank_not_arrival(self):
        # stream-scheduling jitter must not re-label instances: the
        # slower instance gets #2 regardless of which started first
        def rows(order):
            return [{"query": "q1", "wall_ms": w, "start_time": t,
                     "categories": {}, "residual_ms": 0.0}
                    for t, w in order]
        a = rows([(1, 500.0), (2, 1500.0)])
        b = rows([(1, 1500.0), (2, 500.0)])  # flipped start order
        analyze._dedupe_names(a)
        analyze._dedupe_names(b)
        assert {r["query"]: r["wall_ms"] for r in a} \
            == {r["query"]: r["wall_ms"] for r in b} \
            == {"q1": 500.0, "q1#2": 1500.0}

    def test_spanless_failed_query_is_all_residual(self):
        row = analyze.attribute_query(
            {"query": "q", "queryStatus": ["Failed"],
             "queryTimes": [321], "startTime": 1})
        assert row["status"] == "Failed"
        assert row["residual_ms"] == pytest.approx(321.0)


# ----------------------------------------------------------- diff gate

class TestDiffGate:
    def test_golden_run_dirs(self):
        a = analyze.analyze_run(RUN_A)
        b = analyze.analyze_run(RUN_B)
        d = analyze.diff_runs(a, b, pct=10.0, abs_ms=50.0)
        assert not d["passed"]
        assert [e["query"] for e in d["regressions"]] == ["query1"]
        assert [e["query"] for e in d["improvements"]] == ["query2"]
        # query3's +5 ms is below the absolute floor: noise
        assert any(e["query"] == "query3" for e in d["noise"])
        assert d["removed"] == ["query4"]
        assert d["added"] == ["query5"]
        # query2 recompiled (1 -> 2) but is NOT a regression
        assert any(e["query"] == "query2"
                   for e in d["compile_changes"])

    def test_identity_diff_passes(self):
        a = analyze.analyze_run(RUN_A)
        assert analyze.diff_runs(a, a)["passed"]

    def test_gate_thresholds_are_conjunctive(self):
        base = {"q": 100.0}
        # +30% but only 30 ms absolute: below abs floor -> noise
        d = analyze.diff_times(base, {"q": 130.0}, pct=10, abs_ms=50)
        assert not d["regressions"]
        # +60 ms but only 6%: below pct floor -> noise
        d = analyze.diff_times({"q": 1000.0}, {"q": 1060.0},
                               pct=10, abs_ms=50)
        assert not d["regressions"]
        # both floors exceeded -> regression
        d = analyze.diff_times(base, {"q": 200.0}, pct=10, abs_ms=50)
        assert [e["query"] for e in d["regressions"]] == ["q"]

    def test_zero_baseline_regression_not_noise(self):
        # b=0 makes the relative test vacuous; absolute growth must
        # still fail the gate (and format without a pct)
        d = analyze.diff_times({"q": 0.0}, {"q": 5000.0},
                               pct=10, abs_ms=50)
        assert [e["query"] for e in d["regressions"]] == ["q"]
        assert d["regressions"][0]["pct"] is None
        assert "n/a" in analyze.format_diff(
            {**d, "compile_changes": [], "newly_failed": [],
             "passed": False})
        assert analyze.diff_times({"q": 0.0}, {"q": 0.0},
                                  pct=10, abs_ms=50)["regressions"] \
            == []

    def test_kernel_demotion_fails_gate(self):
        # engine/kernels.py choices travel in the summary "kernels"
        # block; a per-query slow-path increase is a planner
        # regression and must fail the diff even with identical times
        a = analyze.analyze_run(RUN_A)
        b = analyze.analyze_run(RUN_A)
        q = a["queries"][0]["query"]
        a["queries"][0] = dict(a["queries"][0],
                               kernels={"join.direct": 2})
        b["queries"][0] = dict(b["queries"][0],
                               kernels={"join.direct": 1,
                                        "join.sortmerge": 1})
        d = analyze.diff_runs(a, b)
        assert [e["query"] for e in d["kernel_changes"]] == [q]
        assert d["kernel_changes"][0]["demoted"] is True
        assert not d["passed"]
        assert "KERNEL-DEMOTED" in analyze.format_diff(d)

    def test_kernel_change_without_demotion_passes(self):
        # a changed mix with NO extra slow-path use is flagged but
        # does not fail (e.g. direct -> matmul is a lateral move)
        a = analyze.analyze_run(RUN_A)
        b = analyze.analyze_run(RUN_A)
        a["queries"][0] = dict(a["queries"][0],
                               kernels={"join.direct": 1})
        b["queries"][0] = dict(b["queries"][0],
                               kernels={"join.matmul": 1})
        d = analyze.diff_runs(a, b)
        assert len(d["kernel_changes"]) == 1
        assert "demoted" not in d["kernel_changes"][0]
        assert d["passed"]
        # kernel-less fixture runs diff with no kernel_changes at all
        clean = analyze.diff_runs(analyze.analyze_run(RUN_A),
                                  analyze.analyze_run(RUN_A))
        assert clean["kernel_changes"] == []

    def test_pre_kernel_baseline_never_demotes(self):
        # a baseline recorded BEFORE the kernel layer (no kernels
        # block) vs a new run whose correct mix includes slow-path
        # kernels: flagged as a change, but the gate must not read
        # the absent counts as zero and hard-fail the first
        # cross-feature diff
        a = analyze.analyze_run(RUN_A)
        b = analyze.analyze_run(RUN_A)
        b["queries"][0] = dict(b["queries"][0],
                               kernels={"join.sortmerge": 2})
        d = analyze.diff_runs(a, b)
        assert len(d["kernel_changes"]) == 1
        assert "demoted" not in d["kernel_changes"][0]
        assert d["passed"]

    def test_attribution_row_carries_kernels_and_roofline(self):
        row = analyze.attribute_query({
            "query": "q", "queryStatus": ["Completed"],
            "queryTimes": [10], "startTime": 1,
            "kernels": {"join.direct": 3},
            "engineTimings": {"ops_per_byte": 1.25,
                              "roofline_frac": 0.4},
        })
        assert row["kernels"] == {"join.direct": 3}
        assert row["ops_per_byte"] == 1.25
        assert row["roofline_frac"] == 0.4
        # and the table renders a roofline column for it
        text = analyze.format_attribution(
            {"queries": [row],
             "totals": {"wall_ms": 10.0,
                        "categories": row["categories"],
                        "residual_ms": row["residual_ms"]},
             "slowest": ["q"]})
        assert "roofline" in text and "1.25@40%" in text

    def test_parse_gate(self):
        assert analyze.parse_gate(None) == {
            "pct": 10.0, "abs_ms": 50.0, "cost_pct": 25.0}
        assert analyze.parse_gate("pct=5,abs_ms=1") == {
            "pct": 5.0, "abs_ms": 1.0, "cost_pct": 25.0}
        with pytest.raises(ValueError):
            analyze.parse_gate("bogus=1")

    def test_newly_failed_query_fails_gate(self):
        a = analyze.analyze_run(RUN_A)
        b = analyze.analyze_run(RUN_A)
        b["queries"][0] = dict(b["queries"][0], status="Failed")
        b["failed"] = [b["queries"][0]["query"]]
        d = analyze.diff_runs(a, b)
        assert d["newly_failed"] == [b["queries"][0]["query"]]
        assert not d["passed"]

    def test_cli_exit_codes(self, capsys):
        import tools.ndsreport as ndsreport
        assert ndsreport.main(["diff", RUN_A, RUN_B,
                               "--gate", "pct=10"]) == 1
        assert ndsreport.main(["diff", RUN_A, RUN_A]) == 0
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "DIFF FAILED" in out


# ----------------------------------------------- maintenance refreshes

def _analysis(times: dict) -> dict:
    """Minimal analyze_run-shaped dict from {name: wall_ms}."""
    rows = [{"query": q, "wall_ms": w, "start_time": i,
             "categories": {"compile": 0.0, "retry_backoff": 0.0,
                            "prefetch_wait": 0.0},
             "residual_ms": 0.0, "compiles": 0,
             "status": "Completed"}
            for i, (q, w) in enumerate(sorted(times.items()))]
    return {"queries": rows, "failed": [], "run_dir": "x"}


class TestMaintGate:
    def test_refresh_regression_fails_gate(self):
        base = _analysis({"query1": 100.0, "LF_WS": 200.0})
        cur = _analysis({"query1": 100.0, "LF_WS": 500.0})
        d = analyze.diff_runs(base, cur, pct=10.0, abs_ms=50.0)
        assert not d["passed"]
        regressed = [e for e in d["maint_changes"]
                     if e.get("regressed")]
        assert [e["query"] for e in regressed] == ["LF_WS"]
        # the refresh function never leaks into the query-side diff
        assert not d["regressions"]
        assert "MAINT-REGRESSED" in analyze.format_diff(d)

    def test_refresh_noise_and_improvement_pass(self):
        base = _analysis({"LF_WS": 200.0, "DF_SS": 400.0})
        cur = _analysis({"LF_WS": 210.0, "DF_SS": 300.0})
        d = analyze.diff_runs(base, cur, pct=10.0, abs_ms=50.0)
        assert d["passed"]
        assert not any(e.get("regressed") for e in d["maint_changes"])

    def test_missing_refresh_function_fails_gate(self):
        base = _analysis({"query1": 100.0, "DF_I": 50.0})
        cur = _analysis({"query1": 100.0})
        d = analyze.diff_runs(base, cur, pct=10.0, abs_ms=50.0)
        assert not d["passed"]
        assert any(e.get("removed") and e["query"] == "DF_I"
                   for e in d["maint_changes"])
        # removed maintenance functions report under MAINT, not the
        # query-side removed list
        assert d["removed"] == []

    def test_query_only_runs_emit_no_maint_block(self):
        a = analyze.analyze_run(RUN_A)
        assert analyze.diff_runs(a, a)["maint_changes"] == []

    def test_delta_column_in_attribution(self):
        row = analyze.attribute_query({
            "query": "q", "queryStatus": ["Completed"],
            "queryTimes": [10], "startTime": 1,
            "engineTimings": {"delta_segments": 2.0,
                              "delta_appended_rows": 40.0,
                              "delta_masked_rows": 12.0},
        })
        assert row["delta_segments"] == 2
        assert row["delta_masked_rows"] == 12
        text = analyze.format_attribution(
            {"queries": [row],
             "totals": {"wall_ms": 10.0,
                        "categories": row["categories"],
                        "residual_ms": row["residual_ms"]},
             "slowest": ["q"]})
        assert "delta" in text and "2s +40 -12" in text


# -------------------------------------------------------------- report

class _TagBalance(html.parser.HTMLParser):
    VOID = {"meta", "br", "hr", "img", "input", "link"}

    def __init__(self):
        super().__init__(convert_charrefs=True)
        self.stack, self.errors = [], []

    def handle_starttag(self, tag, attrs):
        if tag not in self.VOID:
            self.stack.append(tag)

    def handle_endtag(self, tag):
        if not self.stack or self.stack.pop() != tag:
            self.errors.append(tag)


class TestHtmlReport:
    def test_report_smoke_parses(self, tmp_path):
        a = analyze.analyze_run(RUN_A)
        d = analyze.diff_runs(a, analyze.analyze_run(RUN_B))
        paths = analyze.write_outputs(a, str(tmp_path), diff=d)
        text = open(paths["report"]).read()
        p = _TagBalance()
        p.feed(text)
        p.close()
        assert not p.errors and not p.stack
        # per-query bars, slowest table, diff, metrics, timeline all
        # rendered (run_a ships a 2-lane trace.jsonl)
        for marker in ("time attribution", "Slowest", "Diff vs",
                       "Metrics", "Stream overlap timeline",
                       "query1"):
            assert marker in text, marker
        doc = json.load(open(paths["analysis"]))
        assert "trace_events" not in doc
        assert doc["diff"]["regressions"]

    def test_analysis_json_ignored_on_reingest(self, tmp_path):
        # writing artifacts INTO the run dir must not change a second
        # analysis of the same dir
        import shutil
        run = tmp_path / "run"
        shutil.copytree(RUN_A, run)
        first = analyze.analyze_run(str(run))
        analyze.write_outputs(first, str(run))
        second = analyze.analyze_run(str(run))
        assert len(second["queries"]) == len(first["queries"])


# ------------------------------------------------- real CPU power run

@pytest.fixture(scope="module")
def cpu_power_run(tmp_path_factory):
    """A real 3-query NDS power run on the CPU backend, producing an
    honest run dir (summaries + trace + time log)."""
    from nds_tpu.nds import gen_data, streams
    from nds_tpu.nds.power import SUITE
    from nds_tpu.utils import power_core
    from nds_tpu.utils.config import EngineConfig
    root = tmp_path_factory.mktemp("run_analysis_power")
    raw = str(root / "raw")
    sdir = str(root / "streams")
    jsons = str(root / "json")
    gen_data.generate_data_local(0.01, 2, raw, workers=2)
    streams.generate_query_streams(sdir, 1, templates=[96, 7, 93])
    trace = str(root / "json" / "trace.jsonl")
    os.makedirs(jsons, exist_ok=True)
    os.environ["NDS_TPU_TRACE"] = trace
    try:
        failures = power_core.run_query_stream(
            SUITE, raw, os.path.join(sdir, "query_0.sql"),
            str(root / "time.csv"),
            config=EngineConfig(overrides={"engine.backend": "cpu"}),
            input_format="raw", json_summary_folder=jsons)
    finally:
        os.environ.pop("NDS_TPU_TRACE", None)
    assert failures == 0
    return jsons


class TestRealRun:
    def test_attribution_sums_within_1ms(self, cpu_power_run):
        """The ISSUE acceptance criterion: on a fresh 3-query CPU power
        run, every query's categories + residual sum to the reported
        wall-clock within 1 ms."""
        a = analyze.analyze_run(cpu_power_run)
        assert len(a["queries"]) == 3
        for row in a["queries"]:
            total = (sum(row["categories"].values())
                     + row["residual_ms"])
            assert abs(total - row["wall_ms"]) <= 1.0
            # CPU oracle queries still attribute their parse time
            assert row["categories"]["parse_plan"] > 0

    def test_summaries_carry_memory_and_percentiles(self,
                                                    cpu_power_run):
        a = analyze.analyze_run(cpu_power_run)
        rows_with_mem = [r for r in a["queries"] if "hwm_bytes" in r]
        assert rows_with_mem, "no summary carried a memory block"
        assert all(r["hwm_bytes"] > 0 for r in rows_with_mem)
        h = a["metrics"]["histograms"].get("query_seconds")
        assert h and "p50" in h

    def test_summaries_validate_against_schema(self, cpu_power_run):
        from tools.check_trace_schema import validate_summary_file
        # journals (<unit>_queries.json) and merged phase reports are
        # run-dir artifacts but not BenchReports (analyze skips them
        # via the same predicate)
        files = [f for f in os.listdir(cpu_power_run)
                 if analyze.is_report_basename(f)
                 and f != "analysis.json"]
        assert files
        for f in files:
            assert validate_summary_file(
                os.path.join(cpu_power_run, f)) == []

    def test_cli_analyze_prints_table(self, cpu_power_run, tmp_path,
                                      capsys):
        import tools.ndsreport as ndsreport
        rc = ndsreport.main(["analyze", cpu_power_run,
                             "--out", str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "TOTAL" in out and "query96" in out
        assert (tmp_path / "report.html").exists()
        assert (tmp_path / "analysis.json").exists()


# ------------------------------------------------------------ memwatch

class TestMemwatch:
    def test_hwm_monotone_within_query(self):
        tr = memwatch.MemoryTracker()
        tr.reset_query()
        tr.add_live(100)
        assert tr.high_water()["device_hwm_bytes"] == 100
        tr.add_live(50)
        assert tr.high_water()["device_hwm_bytes"] == 150
        tr.sub_live(120)
        # releasing never lowers the mark
        assert tr.high_water()["device_hwm_bytes"] == 150
        tr.add_live(10)
        assert tr.high_water()["device_hwm_bytes"] == 150

    def test_hwm_resets_between_queries(self):
        tr = memwatch.MemoryTracker()
        tr.reset_query()
        tr.add_live(1000)
        tr.sub_live(1000)
        assert tr.high_water()["device_hwm_bytes"] == 1000
        tr.reset_query()
        # new query window: the old peak is gone, pooled live bytes
        # (none here) carry over
        assert tr.high_water() is None
        tr.add_live(10)
        assert tr.high_water() == {"device_hwm_bytes": 10,
                                   "source": "accounted"}

    def test_sub_live_clamps_at_zero(self):
        tr = memwatch.MemoryTracker()
        tr.reset_query()
        tr.add_live(5)
        tr.sub_live(50)
        tr.add_live(7)
        assert tr.high_water()["device_hwm_bytes"] == 7

    def test_gauge_mirrors_hwm(self):
        before = obs_metrics.snapshot()
        memwatch.TRACKER.reset_query()
        memwatch.add_live(1 << 20)
        try:
            assert (obs_metrics.gauge("device_hwm_bytes").value
                    >= 1 << 20)
        finally:
            memwatch.sub_live(1 << 20)
            memwatch.TRACKER.reset_query()
        del before

    def test_table_bytes(self):
        import numpy as np
        from nds_tpu.engine.types import Schema
        from nds_tpu.io.host_table import HostColumn, HostTable
        col = HostColumn(None, np.zeros(8, dtype=np.int64), None,
                         np.ones(8, dtype=bool))
        t = HostTable("t", Schema.of(), {"c": col})
        assert memwatch.table_bytes(t) == 8 * 8 + 8


# ------------------------------------------------------------ snapshot

class TestSnapshotEmitter:
    def test_parse_spec(self):
        assert parse_spec("/tmp/m.json:2.5") == ("/tmp/m.json", 2.5)
        assert parse_spec("/tmp/m.json") == ("/tmp/m.json", 5.0)
        assert om_path_for("/tmp/m.json") == "/tmp/m.om"
        assert om_path_for("/tmp/m") == "/tmp/m.om"

    def test_emitter_writes_valid_openmetrics(self, tmp_path):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("queries_total").inc(3)
        reg.gauge("device_hwm_bytes").set(123456)
        for v in (0.1, 0.2, 0.9):
            reg.histogram("query_seconds").observe(v)
        path = str(tmp_path / "snap.json")
        progress = {"current_query": "query7"}
        snap = MetricsSnapshotter(path, 0.05, registry=reg,
                                  progress=progress)
        snap.start()
        time.sleep(0.15)
        progress["current_query"] = "query93"
        snap.stop()
        doc = json.load(open(path))
        assert doc["counters"]["queries_total"] == 3
        # the final stop() write saw the mutated progress dict
        assert doc["progress"]["current_query"] == "query93"
        om = open(om_path_for(path)).read()
        assert validate_openmetrics(om) == []
        assert "nds_tpu_queries_total 3" in om
        assert 'nds_tpu_query_seconds{quantile="0.50"}' in om
        assert om.rstrip().endswith("# EOF")

    def test_validator_rejects_malformed(self):
        assert validate_openmetrics("nds_tpu_x 1\n") != []  # no EOF
        bad = "# TYPE nds_tpu_x counter\nnds_tpu_x_total NaNish\n# EOF"
        assert validate_openmetrics(bad) != []
        good = to_openmetrics({"counters": {"a_total": 1},
                               "gauges": {"g": 2.5},
                               "histograms": {"h": {
                                   "count": 1, "sum": 2.0,
                                   "p50": 2.0, "p95": 2.0,
                                   "p99": 2.0}}})
        assert validate_openmetrics(good) == []

    def test_power_loop_env_integration(self, tmp_path, monkeypatch):
        # from_env + the power loop's start/stop contract: a run with
        # the env set leaves a final snapshot even if shorter than the
        # interval
        path = str(tmp_path / "live.json")
        monkeypatch.setenv("NDS_TPU_METRICS_SNAP", f"{path}:60")
        snap = MetricsSnapshotter.from_env({"queries_completed": 0})
        assert snap is not None and snap.interval_s == 60.0
        snap.start()
        snap.stop()
        assert json.load(open(path))["progress"] == {
            "queries_completed": 0}
        assert validate_openmetrics(open(om_path_for(path)).read()) \
            == []


# ----------------------------------------------- summary schema gate

class TestSummarySchema:
    def test_rejects_malformed_summaries(self):
        from tools.check_trace_schema import validate_summary
        assert validate_summary([]) != []
        assert validate_summary({"query": "q"}) != []
        base = {"query": "q", "queryStatus": ["Completed"],
                "queryTimes": [10], "startTime": 1, "env": {}}
        assert validate_summary(base) == []
        assert validate_summary(
            {**base, "queryStatus": ["Exploded"]}) != []
        assert validate_summary(
            {**base, "memory": {"device_hwm_bytes": -1,
                                "source": "device"}}) != []
        assert validate_summary(
            {**base, "memory": {"device_hwm_bytes": 5,
                                "source": "martian"}}) != []
        assert validate_summary(
            {**base, "spans": {"name": "", "dur_ms": 1}}) != []
        assert validate_summary(
            {**base, "metrics": {"histograms": {"h": {"count": 1}}}}
        ) != []
        ok = {**base,
              "spans": {"name": "query", "dur_ms": 9.0,
                        "attrs": {}, "children": []},
              "metrics": {"counters": {"c": 1},
                          "histograms": {"h": {"count": 1, "sum": 2.0,
                                               "p99": 2.0}}},
              "memory": {"device_hwm_bytes": 5, "source": "accounted"},
              "retries": 0}
        assert validate_summary(ok) == []


# ------------------------------------------------- tracer atexit flush

class TestTraceFlush:
    def test_flush_salvages_open_roots(self, tmp_path, monkeypatch):
        from nds_tpu.obs.trace import Tracer
        trace_path = str(tmp_path / "t.jsonl")
        monkeypatch.setenv("NDS_TPU_TRACE", trace_path)
        tracer = Tracer(enabled=True)
        span = tracer.begin("query", parent=None, query="doomed")
        tracer.begin("device.run", parent=span)
        # simulated crash: nothing ended, nothing exported yet
        assert not os.path.exists(trace_path)
        tracer.flush_exports(close_roots=True)
        events = [json.loads(ln) for ln in open(trace_path)]
        names = {e["name"] for e in events}
        assert {"query", "device.run"} <= names
        root_ev = next(e for e in events if e["name"] == "query")
        assert root_ev["args"]["truncated"] is True
        # idempotent: a second flush appends nothing
        n = len(events)
        tracer.flush_exports(close_roots=True)
        assert len(open(trace_path).readlines()) == n

    def test_deferred_exports_flush_on_close(self, tmp_path,
                                             monkeypatch):
        from nds_tpu.obs.trace import Tracer
        trace_path = str(tmp_path / "d.jsonl")
        monkeypatch.setenv("NDS_TPU_TRACE", trace_path)
        tracer = Tracer(enabled=True)
        tracer.defer_exports = True
        with tracer.span("query", query="parked"):
            pass
        assert not os.path.exists(trace_path)  # parked, not written
        tracer.flush_exports(close_roots=True)
        assert os.path.exists(trace_path)
        tracer.flush_exports()  # idempotent
        assert len(open(trace_path).readlines()) == 1


# --------------------------------------- merged-incarnation billing

RUN_RESUMED = os.path.join(FIXTURES, "run_resumed")


class TestMergedIncarnations:
    """Resumed runs (README "Preemption & resume") bill each query
    once: the committed run_resumed fixture holds a query reported by
    two incarnations (the kill-between-summary-and-journal window)."""

    def test_merge_resumed_keeps_latest_incarnation(self):
        sums = analyze.load_summaries(RUN_RESUMED)
        assert len(sums) == 4  # the raw dir really holds a duplicate
        merged, dropped = analyze.merge_resumed(sums)
        assert dropped == {"query7": 1}
        by_q = {s["query"]: s for s in merged}
        assert sorted(by_q) == ["query7", "query93", "query96"]
        # the RE-RUN (incarnation 1, Completed) wins over the
        # interrupted incarnation-0 report
        assert by_q["query7"]["incarnation"] == 1
        assert by_q["query7"]["queryStatus"] == ["Completed"]

    def test_analyze_run_bills_merged_queries_once(self):
        a = analyze.analyze_run(RUN_RESUMED, with_trace=False)
        names = [r["query"] for r in a["queries"]]
        assert sorted(names) == ["query7", "query93", "query96"]
        assert a["merged_incarnations"] == {"query7": 1}
        assert a["incarnations"] == 2
        # totals reflect the kept reports only (no double billing)
        assert a["totals"]["wall_ms"] == 120 + 280 + 90
        # the derived merged-*.json phase report is never ingested as
        # a BenchReport (it would double-bill every query)
        assert not analyze.is_report_basename("merged-power-nds.json")

    def test_unresumed_runs_pass_through_untouched(self):
        sums = analyze.load_summaries(RUN_A)
        merged, dropped = analyze.merge_resumed(sums)
        assert merged == sums and dropped == {}

    def test_merge_incarnations_phase_report(self):
        from nds_tpu.utils.report import merge_incarnations
        sums = analyze.load_summaries(RUN_RESUMED)
        doc = merge_incarnations(sums, phase="power-nds")
        assert doc["merged"] is True
        assert doc["incarnations"] == 2
        assert sorted(doc["queries"]) == ["query7", "query93",
                                         "query96"]
        assert doc["queryStatus"] == ["Completed"] * 3
        assert doc["wall_ms_total"] == 120 + 280 + 90
        assert doc["result_digests"]["query7"] == "bbbb333344445555"
