import numpy as np
import pytest

from nds_tpu.datagen import tpch
from nds_tpu.io import csv_io
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h.schema import get_schemas

SF = 0.01  # tiny but non-degenerate: ~60k lineitem rows


@pytest.fixture(scope="module")
def schemas():
    return get_schemas()


class TestTpchGen:
    def test_fixed_tables(self):
        nation = tpch.gen_table("nation", SF)
        assert len(nation["n_nationkey"]) == 25
        assert "GERMANY" in set(nation["n_name"])
        region = tpch.gen_table("region", SF)
        assert list(region["r_name"]) == tpch.REGIONS

    def test_chunking_covers_exactly(self):
        whole = tpch.gen_table("customer", SF, 1, 1)
        parts = [tpch.gen_table("customer", SF, 4, s) for s in range(1, 5)]
        joined = np.concatenate([p["c_custkey"] for p in parts])
        assert np.array_equal(np.sort(joined), np.sort(whole["c_custkey"]))
        # chunks are deterministic
        again = tpch.gen_table("customer", SF, 4, 2)
        assert np.array_equal(again["c_acctbal"], parts[1]["c_acctbal"])

    def test_lineitem_orders_consistency(self):
        orders = tpch.gen_table("orders", SF)
        li = tpch.gen_table("lineitem", SF)
        # every lineitem orderkey exists in orders
        assert np.isin(li["l_orderkey"], orders["o_orderkey"]).all()
        # line numbers start at 1 per order, max 7
        assert li["l_linenumber"].min() == 1
        assert li["l_linenumber"].max() <= 7
        # lineitem chunks partition the same rows
        li_parts = [tpch.gen_table("lineitem", SF, 3, s) for s in range(1, 4)]
        total = sum(len(p["l_orderkey"]) for p in li_parts)
        assert total == len(li["l_orderkey"])
        # extendedprice correlation with part retailprice
        exp = li["l_quantity"] // 100 * tpch.retailprice_cents(li["l_partkey"])
        assert np.array_equal(exp, li["l_extendedprice"])

    def test_custkey_never_multiple_of_three(self):
        orders = tpch.gen_table("orders", SF)
        assert (orders["o_custkey"] % 3 != 0).all()

    def test_dates_in_range(self):
        li = tpch.gen_table("lineitem", SF)
        assert li["l_shipdate"].min() >= tpch.STARTDATE
        assert (li["l_receiptdate"] > li["l_shipdate"]).all()
        # both linestatus values occur (split date logic)
        assert set(li["l_linestatus"]) == {"O", "F"}

    def test_partsupp_spread(self):
        ps = tpch.gen_table("partsupp", SF)
        # 4 distinct suppliers per part
        assert len(ps["ps_partkey"]) == 4 * tpch.table_rows("part", SF)
        first_part = ps["ps_suppkey"][ps["ps_partkey"] == 1]
        assert len(set(first_part)) == 4


class TestIO:
    def test_tbl_roundtrip(self, tmp_path, schemas):
        arrays = tpch.gen_table("supplier", SF)
        schema = schemas["supplier"]
        p = str(tmp_path / "supplier.tbl")
        csv_io.write_tbl(arrays, schema, p)
        t = csv_io.read_tbl(p, "supplier", schema)
        assert t.nrows == len(arrays["s_suppkey"])
        assert np.array_equal(t.column("s_suppkey").values, arrays["s_suppkey"])
        # decimal scale preserved exactly through text
        assert np.array_equal(t.column("s_acctbal").values, arrays["s_acctbal"])
        # strings decode back
        assert list(t.column("s_name").decode()[:3]) == list(arrays["s_name"][:3])

    def test_parquet_roundtrip(self, tmp_path, schemas):
        arrays = tpch.gen_table("orders", SF, 4, 1)
        schema = schemas["orders"]
        ht = from_arrays("orders", schema, arrays)
        p = str(tmp_path / "orders.parquet")
        csv_io.write_parquet(ht, p)
        back = csv_io.read_parquet(p, "orders", schema)
        assert back.nrows == ht.nrows
        assert np.array_equal(back.column("o_orderkey").values,
                              ht.column("o_orderkey").values)
        assert np.array_equal(back.column("o_totalprice").values,
                              ht.column("o_totalprice").values)
        assert np.array_equal(back.column("o_orderdate").values,
                              ht.column("o_orderdate").values)
        assert list(back.column("o_orderpriority").decode()[:5]) == \
            list(ht.column("o_orderpriority").decode()[:5])

    @pytest.mark.parametrize("fmt", ["orc", "json"])
    def test_format_roundtrip(self, tmp_path, schemas, fmt):
        """Non-parquet warehouse formats (`nds/nds_transcode.py:69-152`
        writes parquet/orc/avro/json; avro has no codec here)."""
        arrays = tpch.gen_table("orders", SF, 4, 1)
        schema = schemas["orders"]
        ht = from_arrays("orders", schema, arrays)
        p = str(tmp_path / ("orders" + csv_io.FORMAT_EXT[fmt]))
        csv_io.write_table(ht, p, fmt)
        back = csv_io.read_table_fmt(p, "orders", schema, fmt)
        assert back.nrows == ht.nrows
        assert np.array_equal(back.column("o_orderkey").values,
                              ht.column("o_orderkey").values)
        assert np.array_equal(back.column("o_totalprice").values,
                              ht.column("o_totalprice").values)
        assert np.array_equal(back.column("o_orderdate").values,
                              ht.column("o_orderdate").values)
        assert list(back.column("o_orderpriority").decode()[:5]) == \
            list(ht.column("o_orderpriority").decode()[:5])

    def test_avro_raises_clearly(self, tmp_path, schemas):
        ht = from_arrays("orders", schemas["orders"],
                         tpch.gen_table("orders", SF, 4, 1))
        with pytest.raises(ValueError, match="avro"):
            csv_io.write_table(ht, str(tmp_path / "o.avro"), "avro")

    def test_string_codes_sorted(self, schemas):
        arrays = tpch.gen_table("customer", SF, 8, 3)
        ht = from_arrays("customer", schemas["customer"], arrays)
        col = ht.column("c_mktsegment")
        d = col.dictionary
        assert all(d[i] <= d[i + 1] for i in range(len(d) - 1))
        # code comparison == lexicographic comparison
        decoded = col.decode()
        order_by_code = np.argsort(col.values, kind="stable")
        assert list(decoded[order_by_code]) == sorted(decoded)
