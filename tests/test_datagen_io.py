"""Datagen + IO layer tests: deterministic generators, dbgen .tbl
layout, parquet/orc/json/avro warehouse round-trips, dictionary
encoding (reference surface: nds/nds_gen_data.py + nds_transcode.py)."""

import numpy as np
import pytest

from nds_tpu.datagen import tpch
from nds_tpu.io import csv_io
from nds_tpu.io.host_table import from_arrays
from nds_tpu.nds_h.schema import get_schemas

SF = 0.01  # tiny but non-degenerate: ~60k lineitem rows


@pytest.fixture(scope="module")
def schemas():
    return get_schemas()


class TestTpchGen:
    def test_fixed_tables(self):
        nation = tpch.gen_table("nation", SF)
        assert len(nation["n_nationkey"]) == 25
        assert "GERMANY" in set(nation["n_name"])
        region = tpch.gen_table("region", SF)
        assert list(region["r_name"]) == tpch.REGIONS

    def test_chunking_covers_exactly(self):
        whole = tpch.gen_table("customer", SF, 1, 1)
        parts = [tpch.gen_table("customer", SF, 4, s) for s in range(1, 5)]
        joined = np.concatenate([p["c_custkey"] for p in parts])
        assert np.array_equal(np.sort(joined), np.sort(whole["c_custkey"]))
        # chunks are deterministic
        again = tpch.gen_table("customer", SF, 4, 2)
        assert np.array_equal(again["c_acctbal"], parts[1]["c_acctbal"])

    def test_lineitem_orders_consistency(self):
        orders = tpch.gen_table("orders", SF)
        li = tpch.gen_table("lineitem", SF)
        # every lineitem orderkey exists in orders
        assert np.isin(li["l_orderkey"], orders["o_orderkey"]).all()
        # line numbers start at 1 per order, max 7
        assert li["l_linenumber"].min() == 1
        assert li["l_linenumber"].max() <= 7
        # lineitem chunks partition the same rows
        li_parts = [tpch.gen_table("lineitem", SF, 3, s) for s in range(1, 4)]
        total = sum(len(p["l_orderkey"]) for p in li_parts)
        assert total == len(li["l_orderkey"])
        # extendedprice correlation with part retailprice
        exp = li["l_quantity"] // 100 * tpch.retailprice_cents(li["l_partkey"])
        assert np.array_equal(exp, li["l_extendedprice"])

    def test_custkey_never_multiple_of_three(self):
        orders = tpch.gen_table("orders", SF)
        assert (orders["o_custkey"] % 3 != 0).all()

    def test_dates_in_range(self):
        li = tpch.gen_table("lineitem", SF)
        assert li["l_shipdate"].min() >= tpch.STARTDATE
        assert (li["l_receiptdate"] > li["l_shipdate"]).all()
        # both linestatus values occur (split date logic)
        assert set(li["l_linestatus"]) == {"O", "F"}

    def test_partsupp_spread(self):
        ps = tpch.gen_table("partsupp", SF)
        # 4 distinct suppliers per part
        assert len(ps["ps_partkey"]) == 4 * tpch.table_rows("part", SF)
        first_part = ps["ps_suppkey"][ps["ps_partkey"] == 1]
        assert len(set(first_part)) == 4


class TestIO:
    def test_tbl_roundtrip(self, tmp_path, schemas):
        arrays = tpch.gen_table("supplier", SF)
        schema = schemas["supplier"]
        p = str(tmp_path / "supplier.tbl")
        csv_io.write_tbl(arrays, schema, p)
        t = csv_io.read_tbl(p, "supplier", schema)
        assert t.nrows == len(arrays["s_suppkey"])
        assert np.array_equal(t.column("s_suppkey").values, arrays["s_suppkey"])
        # decimal scale preserved exactly through text
        assert np.array_equal(t.column("s_acctbal").values, arrays["s_acctbal"])
        # strings decode back
        assert list(t.column("s_name").decode()[:3]) == list(arrays["s_name"][:3])

    def test_parquet_roundtrip(self, tmp_path, schemas):
        arrays = tpch.gen_table("orders", SF, 4, 1)
        schema = schemas["orders"]
        ht = from_arrays("orders", schema, arrays)
        p = str(tmp_path / "orders.parquet")
        csv_io.write_parquet(ht, p)
        back = csv_io.read_parquet(p, "orders", schema)
        assert back.nrows == ht.nrows
        assert np.array_equal(back.column("o_orderkey").values,
                              ht.column("o_orderkey").values)
        assert np.array_equal(back.column("o_totalprice").values,
                              ht.column("o_totalprice").values)
        assert np.array_equal(back.column("o_orderdate").values,
                              ht.column("o_orderdate").values)
        assert list(back.column("o_orderpriority").decode()[:5]) == \
            list(ht.column("o_orderpriority").decode()[:5])

    @pytest.mark.parametrize("fmt", ["orc", "json", "avro"])
    def test_format_roundtrip(self, tmp_path, schemas, fmt):
        """Non-parquet warehouse formats (`nds/nds_transcode.py:69-152`
        writes parquet/orc/avro/json; avro via io/avro_io.py)."""
        arrays = tpch.gen_table("orders", SF, 4, 1)
        schema = schemas["orders"]
        ht = from_arrays("orders", schema, arrays)
        p = str(tmp_path / ("orders" + csv_io.FORMAT_EXT[fmt]))
        csv_io.write_table(ht, p, fmt)
        back = csv_io.read_table_fmt(p, "orders", schema, fmt)
        assert back.nrows == ht.nrows
        assert np.array_equal(back.column("o_orderkey").values,
                              ht.column("o_orderkey").values)
        assert np.array_equal(back.column("o_totalprice").values,
                              ht.column("o_totalprice").values)
        assert np.array_equal(back.column("o_orderdate").values,
                              ht.column("o_orderdate").values)
        assert list(back.column("o_orderpriority").decode()[:5]) == \
            list(ht.column("o_orderpriority").decode()[:5])

    def test_avro_container_layout_and_nulls(self, tmp_path, schemas):
        """The avro file is a spec Object Container File (magic,
        schema+codec metadata, sync-framed deflate blocks) and NULLs
        round-trip through the ["null", T] unions."""
        import json as _json
        import numpy as np_
        from nds_tpu.engine.types import INT32, Schema, decimal, varchar
        from nds_tpu.io import avro_io
        sch = Schema.of(("k", INT32, False), ("v", decimal(12, 2), True),
                        ("s", varchar(10), True))
        arrays = {
            "k": np_.arange(5, dtype=np_.int32),
            "v": np_.array([100, -205, 0, 9, 7], dtype=np_.int64),
            "v#null": np_.array([True, True, False, True, False]),
            "s": np_.array(["a", "b", "", "d", ""], dtype=object),
            "s#null": np_.array([True, True, False, True, False]),
        }
        ht = from_arrays("t", sch, arrays)
        p = str(tmp_path / "t.avro")
        avro_io.write_avro(ht, p, sch, codec="deflate")
        blob = open(p, "rb").read()
        assert blob[:4] == b"Obj\x01"
        assert b"avro.schema" in blob and b"avro.codec" in blob
        # decode the header's metadata map with the module's own varint
        # reader to check the embedded schema JSON
        import io as _io
        hdr = _io.BytesIO(blob[4:])
        meta = {}
        while (cnt := avro_io._read_long(hdr)) != 0:
            for _ in range(cnt):
                key = avro_io._read_bytes(hdr).decode()
                meta[key] = avro_io._read_bytes(hdr)
        parsed = _json.loads(meta["avro.schema"])
        assert meta["avro.codec"] == b"deflate"
        assert [f["name"] for f in parsed["fields"]] == ["k", "v", "s"]
        assert parsed["fields"][1]["type"][1]["logicalType"] == "decimal"
        back = avro_io.read_avro(p, "t", sch)
        assert np_.array_equal(back.column("k").values,
                               arrays["k"].astype(np_.int32))
        assert np_.array_equal(back.column("v").null_mask,
                               arrays["v#null"])
        vm = arrays["v#null"]
        assert np_.array_equal(back.column("v").values[vm],
                               arrays["v"][vm])
        got_s = back.column("s").decode()
        assert [got_s[i] for i in (0, 1, 3)] == ["a", "b", "d"]
        assert got_s[2] is None and got_s[4] is None

    def test_string_codes_sorted(self, schemas):
        arrays = tpch.gen_table("customer", SF, 8, 3)
        ht = from_arrays("customer", schemas["customer"], arrays)
        col = ht.column("c_mktsegment")
        d = col.dictionary
        assert all(d[i] <= d[i + 1] for i in range(len(d) - 1))
        # code comparison == lexicographic comparison
        decoded = col.decode()
        order_by_code = np.argsort(col.values, kind="stable")
        assert list(decoded[order_by_code]) == sorted(decoded)


def test_read_paths_auto_mixed_formats(tmp_path):
    """Snapshot manifests mix the load-time warehouse format with the
    parquet version files maintenance commits; read_paths_auto buckets
    per extension and rebuilds one table (csv_io.read_paths_auto)."""
    from nds_tpu.engine.types import INT32, Schema, varchar
    from nds_tpu.io.host_table import from_arrays as fa

    sch = Schema.of(("k", INT32, False), ("s", varchar(8), True))
    a = fa("t", sch, {
        "k": np.arange(3, dtype=np.int32),
        "s": np.array(["x", "y", "z"], dtype=object),
        "s#null": np.array([True, False, True]),
    })
    b = fa("t", sch, {
        "k": np.arange(10, 14, dtype=np.int32),
        "s": np.array(["p", "q", "r", "s"], dtype=object),
        "s#null": np.array([True, True, True, False]),
    })
    p1 = str(tmp_path / "base.avro")
    p2 = str(tmp_path / "version.parquet")
    csv_io.write_table(a, p1, "avro")
    csv_io.write_table(b, p2, "parquet")
    t = csv_io.read_paths_auto([p1, p2], "t", sch, "avro")
    assert t.nrows == 7
    assert list(t.column("k").values) == [0, 1, 2, 10, 11, 12, 13]
    got = t.column("s").decode()
    assert got[0] == "x" and got[1] is None and got[3] == "p"
    assert got[6] is None
