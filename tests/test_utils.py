"""Utility-layer tests: layered config precedence, reference-format
time logs and JSON summaries, guard checks (reference surface:
nds/check.py, PysparkBenchReport.py, properties files)."""

import json
import os

import pytest

from nds_tpu.utils import check
from nds_tpu.utils.config import EngineConfig, load_properties
from nds_tpu.utils.report import BenchReport, TaskFailureCollector, redact_env
from nds_tpu.utils.timelog import TimeLog


class TestCheck:
    def test_valid_range(self):
        assert check.valid_range("1,10", 10) == (1, 10)
        assert check.valid_range("3,3", 5) == (3, 3)
        with pytest.raises(check.CheckError):
            check.valid_range("0,5", 10)
        with pytest.raises(check.CheckError):
            check.valid_range("5,3", 10)
        with pytest.raises(check.CheckError):
            check.valid_range("1,11", 10)
        with pytest.raises(check.CheckError):
            check.valid_range("junk", 10)

    def test_parallel_value_type(self):
        assert check.parallel_value_type("2") == 2
        with pytest.raises(check.CheckError):
            check.parallel_value_type("1")
        with pytest.raises(check.CheckError):
            check.parallel_value_type("x")

    def test_json_summary_folder(self, tmp_path):
        check.check_json_summary_folder(None)
        check.check_json_summary_folder(str(tmp_path / "new"))  # absent ok
        empty = tmp_path / "empty"
        empty.mkdir()
        check.check_json_summary_folder(str(empty))
        full = tmp_path / "full"
        full.mkdir()
        (full / "x.json").write_text("{}")
        with pytest.raises(check.CheckError):
            check.check_json_summary_folder(str(full))

    def test_query_subset(self):
        qd = {"query1": "...", "query2": "..."}
        check.check_query_subset_exists(qd, ["query1"])
        with pytest.raises(check.CheckError):
            check.check_query_subset_exists(qd, ["query9"])


class TestConfig:
    def test_load_properties_env_subst(self, tmp_path, monkeypatch):
        p = tmp_path / "t.properties"
        p.write_text(
            "# comment\n"
            "engine.backend=${NDS_BACKEND:-tpu}\n"
            "engine.mesh.shards=8\n")
        conf = load_properties(str(p))
        assert conf["engine.backend"] == "tpu"
        monkeypatch.setenv("NDS_BACKEND", "cpu")
        conf = load_properties(str(p))
        assert conf["engine.backend"] == "cpu"

    def test_precedence(self, tmp_path):
        tpl = tmp_path / "a.template"
        tpl.write_text("engine.floats=true\nengine.mesh.shards=4\n")
        prop = tmp_path / "b.properties"
        prop.write_text("engine.floats=false\n")
        cfg = EngineConfig(str(tpl), str(prop), {"engine.mesh.shards": 2})
        assert cfg.get_bool("engine.floats") is False
        assert cfg.get_int("engine.mesh.shards") == 2
        # defaults survive when unset
        assert cfg.get_int("engine.concurrent_tasks") == 2


class TestReport:
    def test_redaction(self):
        env = {"MY_TOKEN": "x", "API_SECRET": "y", "PASSWORD": "z",
               "AWS_ACCESS_KEY_ID": "k", "HOME": "/root"}
        red = redact_env(env)
        assert red == {"HOME": "/root"}

    def test_report_success_and_filename(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        r = BenchReport("query1")
        summary = r.report_on(lambda x: x + 1, 41)
        assert summary["queryStatus"] == ["Completed"]
        assert summary["query"] == "query1"
        assert len(summary["queryTimes"]) == 1
        path = r.write_summary(prefix="pow")
        assert path == f"pow-query1-{summary['startTime']}.json"
        with open(path) as f:
            loaded = json.load(f)
        assert loaded["queryStatus"] == ["Completed"]
        assert r.is_success()

    def test_report_failure(self):
        r = BenchReport("query2")
        def boom():
            raise RuntimeError("kaput")
        s = r.report_on(boom)
        assert s["queryStatus"] == ["Failed"]
        assert "kaput" in s["exceptions"][0]
        assert not r.is_success()

    def test_task_failures(self):
        r = BenchReport("query3")
        def flaky():
            TaskFailureCollector.notify("retry on padded overflow")
        s = r.report_on(flaky)
        assert s["queryStatus"] == ["CompletedWithTaskFailures"]
        assert not r.is_success()


class TestTimeLog:
    def test_roundtrip(self, tmp_path):
        tl = TimeLog("app-123")
        tl.add("query1", 1500)
        tl.add("query2", 2500)
        p = str(tmp_path / "time.csv")
        tl.write(p)
        rows = TimeLog.read(p)
        assert rows == [("app-123", "query1", 1500), ("app-123", "query2", 2500)]
