"""SQL frontend tests: lexer/parser AST shapes and planner rules over
the TPC-DS/TPC-H grammar subset (the engine half the reference
delegates to Spark's parser)."""

import pytest

from nds_tpu.sql import ast
from nds_tpu.sql.parser import ParseError, parse
from nds_tpu.nds_h import streams


class TestParser:
    def test_simple_select(self):
        s = parse("select a, b as bee from t where a > 3 order by bee desc limit 5")
        assert [i.alias for i in s.items] == [None, "bee"]
        assert isinstance(s.where, ast.BinOp) and s.where.op == ">"
        assert s.order_by[0].ascending is False
        assert s.limit == 5

    def test_date_interval(self):
        s = parse("select * from t where d <= date '1998-12-01' - interval '90' day")
        cmp = s.where
        assert isinstance(cmp.right, ast.BinOp) and cmp.right.op == "-"
        assert isinstance(cmp.right.right, ast.Interval)
        assert cmp.right.right.amount == 90 and cmp.right.right.unit == "day"

    def test_case_when(self):
        s = parse("select sum(case when x = 1 then y else 0 end) from t")
        f = s.items[0].expr
        assert isinstance(f, ast.FuncCall) and f.name == "sum"
        assert isinstance(f.args[0], ast.CaseWhen)

    def test_exists_and_in(self):
        s = parse("select * from o where exists (select * from l where "
                  "l_ok = o_ok) and k in (1, 2, 3) and j not in "
                  "(select x from y)")
        conj = s.where
        assert isinstance(conj, ast.BinOp) and conj.op == "and"

    def test_left_join_on(self):
        s = parse("select c from customer left outer join orders on "
                  "c_custkey = o_custkey and o_comment not like '%x%y%'")
        assert len(s.joins) == 1 and s.joins[0].kind == "left"

    def test_nested_derived(self):
        s = parse("select a from (select b as a from t) as sub group by a")
        assert isinstance(s.from_tables[0], ast.SubqueryRef)
        assert s.from_tables[0].alias == "sub"

    def test_create_drop_view(self):
        v = parse("create view rev (s_no, total) as select a, sum(b) from t group by a")
        assert isinstance(v, ast.CreateView)
        assert v.columns == ["s_no", "total"]
        d = parse("drop view rev")
        assert isinstance(d, ast.DropView)

    def test_substring_and_extract(self):
        s = parse("select substring(c_phone, 1, 2), extract(year from d) from t")
        assert isinstance(s.items[0].expr, ast.Substring)
        assert isinstance(s.items[1].expr, ast.Extract)

    def test_errors(self):
        with pytest.raises(ParseError):
            parse("select from t")
        with pytest.raises(ParseError):
            parse("select a from t where")
        with pytest.raises(ParseError):
            parse("select a from t limit x")

    def test_all_22_templates_parse(self):
        for qn in range(1, 23):
            for stmt in streams.statements(qn):
                parse(stmt)


class TestStreams:
    def test_stream_generation_and_parse(self, tmp_path):
        paths = streams.generate_query_streams(str(tmp_path), 3, rng_seed=42)
        assert len(paths) == 3
        qd = streams.parse_query_stream(paths[0])
        # stream 0 sequential, q15 split into 3 parts -> 24 entries
        assert len(qd) == 24
        assert list(qd)[0] == "query1"
        assert "query15_part1" in qd and "query15_part3" in qd
        assert qd["query15_part1"].lower().startswith("create view")
        # throughput streams are permuted but complete
        qd1 = streams.parse_query_stream(paths[1])
        assert len(qd1) == 24
        assert list(qd1) != list(qd)

    def test_permutations_deterministic(self, tmp_path):
        a = streams.generate_query_streams(str(tmp_path / "a"), 2, rng_seed=7)
        b = streams.generate_query_streams(str(tmp_path / "b"), 2, rng_seed=7)
        assert open(a[1]).read() == open(b[1]).read()

    def test_single_query(self, tmp_path):
        p = streams.generate_single_query(str(tmp_path), 6)
        qd = streams.parse_query_stream(p)
        assert list(qd) == ["query6"]
